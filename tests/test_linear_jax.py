"""Device (JAX) frontier search vs. the host reference engine."""

import random

import numpy as np
import pytest

from comdb2_tpu.checker import analysis
from comdb2_tpu.checker import linear_host, linear_jax as LJ
from comdb2_tpu.checker.linear import _next_pow2
from comdb2_tpu.models.memo import memo as make_memo
from comdb2_tpu.models import model as M
from comdb2_tpu.ops import op as O
from comdb2_tpu.ops.packed import pack_history

import histgen


def device_check(model, history, F=64):
    packed = pack_history(history)
    mm = make_memo(model, packed)
    P = max(1, len(packed.process_table))
    stream = LJ.make_stream(packed)
    status, fail_at, n = LJ.check_device(
        LJ.pad_succ(mm.succ), *stream, F=F, P=P)
    return int(status), int(fail_at), int(n)


def test_device_simple_valid():
    h = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
         O.invoke(0, "read", None), O.ok(0, "read", 1)]
    status, _, n = device_check(M.register(), h)
    assert status == LJ.VALID and n >= 1


def test_device_simple_invalid():
    h = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
         O.invoke(0, "read", None), O.ok(0, "read", 2)]
    status, fail_at, _ = device_check(M.register(), h)
    assert status == LJ.INVALID
    assert fail_at == 3


def test_device_overflow_is_unknown():
    # many concurrent crashed writes of distinct values -> frontier blowup
    h = []
    for i in range(12):
        h.append(O.invoke(i, "write", i))
        h.append(O.info(i, "write", i))
    h += [O.invoke(100, "read", None), O.ok(100, "read", 5)]
    status, _, _ = device_check(M.register(), h, F=4)
    assert status == LJ.UNKNOWN


@pytest.mark.parametrize("seed", range(40))
def test_device_matches_host_random(seed):
    rng = random.Random(77_000 + seed)
    h = histgen.register_history(rng, n_procs=rng.randint(2, 4),
                                 n_events=rng.randint(4, 16),
                                 p_info=0.1)
    if rng.random() < 0.6:
        h = histgen.mutate(rng, h)
    model = M.cas_register()
    packed = pack_history(h)
    mm = make_memo(model, packed)
    hr = linear_host.check(mm, packed)
    status, fail_at, _ = device_check(model, h, F=256)
    assert status in (LJ.VALID, LJ.INVALID)
    assert (status == LJ.VALID) == hr.valid, f"host={hr.valid}"
    if status == LJ.INVALID:
        assert fail_at == hr.op_index


@pytest.mark.parametrize("packed_path", [False, True])
def test_seg2_adaptive_matches_host_fuzz(packed_path):
    """The two-tier engine (small closure + per-segment escalation) must
    agree with the host reference on verdicts AND fail indices. Shapes
    are bucketed so the whole fuzz shares a few compiled programs.

    packed_path=True sizes the state space so the two-word packed dedup
    (incl. the returned-first bit at hi bit 29) is what runs; False
    forces the full-lexsort fallback (P=8 slots never fit the budget)."""
    if packed_path:
        P, sizes = 4, dict(n_states=16, n_transitions=16)
        assert LJ.pack_bits(16, 16, P)[2]     # the budget must fit
    else:
        P, sizes = 8, dict(n_states=64, n_transitions=64)
        assert not LJ.pack_bits(64, 64, P)[2]
    hits = 0
    for seed in range(88_000, 88_120):
        rng = random.Random(seed)
        h = histgen.register_history(
            rng, n_procs=rng.randint(2, 3 if packed_path else 5),
            n_events=rng.randint(6, 40),
            p_info=0.05 if packed_path else 0.15,
            values=2 if packed_path else 5)
        if rng.random() < 0.5:
            h = histgen.mutate(rng, h)
        packed = pack_history(h)
        mm = make_memo(M.cas_register(), packed)
        if (len(packed.process_table) > P
                or mm.n_states > sizes["n_states"]
                or mm.n_transitions > sizes["n_transitions"]):
            continue
        segs = LJ.make_segments(packed, s_pad=32, k_pad=8)
        if segs.inv_proc.shape != (32, 8):
            continue
        hr = linear_host.check(mm, packed, max_configs=1 << 18)
        # sizes are bucketed to keep one jit signature; padding ids are
        # unreachable so semantics are unchanged
        status, fa, _ = LJ.check_device_seg2(
            LJ.pad_succ(mm.succ, sizes["n_states"],
                        sizes["n_transitions"]),
            segs.inv_proc, segs.inv_tr,
            segs.ok_proc, segs.depth, F=64, Fs=8, P=P, **sizes)
        if int(status) == LJ.UNKNOWN:
            continue            # F=64 overflow: sound, just imprecise
        assert (int(status) == LJ.VALID) == hr.valid, f"seed={seed}"
        if int(status) == LJ.INVALID:
            assert int(segs.seg_index[int(fa)]) == hr.op_index, \
                f"seed={seed}"
        hits += 1
    assert hits > 60      # the fuzz must mostly exercise the engine


def test_analysis_device_backend():
    rng = random.Random(5)
    h = histgen.register_history(rng, n_procs=3, n_events=40)
    a = analysis(M.cas_register(), h, backend="device")
    assert a.valid is True
    h2 = histgen.mutate(random.Random(6), h)
    from comdb2_tpu.checker.brute import brute_valid
    a2 = analysis(M.cas_register(), h2, backend="device")
    assert a2.valid == brute_valid(M.cas_register(), h2)
    if a2.valid is False:
        assert a2.op is not None


def test_analysis_auto_small_uses_host():
    h = [O.invoke(0, "write", 1), O.ok(0, "write", 1)]
    a = analysis(M.register(), h)
    assert a.valid is True
    assert a.info["backend"] == "host"


# --- batched ----------------------------------------------------------------

def test_device_batch():
    from comdb2_tpu.checker.batch import pack_batch, check_batch

    model = M.cas_register()
    histories, want = [], []
    for seed in range(16):
        rng = random.Random(31_000 + seed)
        h = histgen.register_history(rng, n_procs=3,
                                     n_events=rng.randint(6, 14))
        if seed % 2:
            h = histgen.mutate(rng, h)
        histories.append(h)
        packed = pack_history(h)
        mm = make_memo(model, packed)
        want.append(linear_host.check(mm, packed).valid)
    batch = pack_batch(histories, model)
    status, fail_at, n = check_batch(batch, F=128)
    got = [s == LJ.VALID for s in status]
    assert got == want


def test_flat_engine_matches_host_fuzz():
    """The flat-batch engine (one frontier tensor, batch id in the sort
    key) must agree with the host engine on random mixed batches."""
    from comdb2_tpu.checker.batch import pack_batch, check_batch

    model = M.cas_register()
    for round_ in range(4):
        histories, want = [], []
        for seed in range(12):
            rng = random.Random(77_000 + round_ * 100 + seed)
            h = histgen.register_history(
                rng, n_procs=rng.randint(2, 4),
                n_events=rng.randint(5, 24),
                p_info=0.1 if seed % 3 == 0 else 0.0)
            if seed % 2:
                h = histgen.mutate(rng, h)
            histories.append(h)
            packed = pack_history(h)
            mm = make_memo(model, packed)
            want.append(linear_host.check(mm, packed).valid)
        batch = pack_batch(histories, model)
        status, fail_at, n = check_batch(batch, F=128, engine="flat")
        got = [s == LJ.VALID for s in status]
        assert got == want, (round_, got, want)


def test_flat_engines_overflow_unknown():
    """A batch lane whose frontier exceeds F must come back UNKNOWN,
    not a wrong definite verdict — in both flat engines."""
    from comdb2_tpu.checker.batch import pack_batch, check_batch

    model = M.cas_register()
    rng = random.Random(99)
    # concurrent pending ops -> frontier larger than a tiny F
    # (p_info=0 keeps the process table narrow so the key budget fits)
    wide = histgen.register_history(rng, n_procs=4, n_events=60,
                                    p_info=0.0)
    small = histgen.register_history(random.Random(1), n_procs=2,
                                     n_events=8, p_info=0.0)
    batch = pack_batch([wide, small], model)
    for engine in ("flat", "keys"):
        status, _, _ = check_batch(batch, F=2, engine=engine)
        assert status[0] == LJ.UNKNOWN, (engine, status)
        assert status[1] in (LJ.VALID, LJ.UNKNOWN)


def test_pack_bits_rejects_fragmented_budgets():
    """fits must simulate the greedy per-word split: totals that fit 61
    bits can still overflow one word once fields can't straddle."""
    sb, tb, fits = LJ.pack_bits(1 << 20, (1 << 20) - 2, 2)
    assert not fits                     # 20+20+20: hi word gets 40 bits
    assert LJ.pack_bits(8, 30, 8)[2]    # 3 + 8*5 splits fine
    bb, stb, slb, ffits = LJ.flat_pack_bits(2, 1 << 18, (1 << 20) - 2, 2)
    assert not ffits
    # and KeyLayout agrees where flat_pack_bits says no
    assert not LJ.KeyLayout(2, 1 << 18, (1 << 20) - 2, 2).fits


def test_pack_words_injective_when_fits():
    """Whenever pack_bits accepts a shape, distinct configs must get
    distinct fingerprints."""
    import jax.numpy as jnp

    rng = random.Random(4)
    for n_states, n_tr, P in ((6, 26, 4), (1 << 14, 14, 2), (4, 1 << 13, 2)):
        sb, tb, fits = LJ.pack_bits(n_states, n_tr, P)
        if not fits:
            continue
        rows = set()
        configs = []
        for _ in range(200):
            st = rng.randrange(n_states)
            sl = tuple(rng.randrange(-2, n_tr) for _ in range(P))
            if (st, sl) not in rows:
                rows.add((st, sl))
                configs.append((st, sl))
        states = jnp.asarray([c[0] for c in configs], jnp.int32)
        slots = jnp.asarray([c[1] for c in configs], jnp.int32)
        plan = LJ.make_pack_plan(n_states, n_tr, P)
        assert plan is not None and plan.n_words <= 2
        words = LJ._pack_plan_words(states, slots, plan)
        pairs = set(zip(*(np.asarray(w).tolist() for w in words)))
        assert len(pairs) == len(configs), (n_states, n_tr, P)


def test_malformed_history_isolated_in_batch():
    """A double-pending history (bypassing history.complete) must come
    back `unknown` without poisoning the rest of the batch — the
    check-safe semantics of checker.clj:54-64 applied per key."""
    from comdb2_tpu.checker.batch import pack_batch, check_batch
    from comdb2_tpu.ops import op as O

    good = [O.invoke(0, "write", 1), O.ok(0, "write", 1)]
    bad = [O.invoke(0, "write", 1), O.invoke(0, "write", 2),
           O.ok(0, "write", 2)]
    # build the malformed history by hand: complete() would raise
    bad = [op.with_(index=i) for i, op in enumerate(bad)]
    packed_bad = pack_history(bad, completed=True)
    batch = pack_batch([good, packed_bad, good], M.cas_register())
    for engine in ("keys", "flat", "vmap"):
        status, fail_at, n = check_batch(batch, F=32, engine=engine)
        assert status[0] == LJ.VALID and status[2] == LJ.VALID
        assert status[1] == LJ.UNKNOWN, (engine, status)
        assert fail_at[1] == -1 and n[1] == 0


def test_keys_engine_matches_host_fuzz():
    from comdb2_tpu.checker.batch import pack_batch, check_batch

    model = M.cas_register()
    for round_ in range(3):
        histories, want = [], []
        for seed in range(10):
            rng = random.Random(55_000 + round_ * 100 + seed)
            h = histgen.register_history(
                rng, n_procs=rng.randint(2, 4),
                n_events=rng.randint(5, 24),
                p_info=0.1 if seed % 3 == 0 else 0.0)
            if seed % 2:
                h = histgen.mutate(rng, h)
            histories.append(h)
            packed = pack_history(h)
            mm = make_memo(model, packed)
            want.append(linear_host.check(mm, packed).valid)
        batch = pack_batch(histories, model)
        status, fail_at, n = check_batch(batch, F=128, engine="keys")
        got = [s == LJ.VALID for s in status]
        assert got == want, (round_, got, want)


def test_device_batch_sharded_mesh():
    import jax
    from jax.sharding import Mesh
    from comdb2_tpu.checker.batch import pack_batch, check_batch

    model = M.cas_register()
    histories = []
    for seed in range(8):
        rng = random.Random(41_000 + seed)
        histories.append(histgen.register_history(rng, n_procs=3,
                                                  n_events=10))
    batch = pack_batch(histories, model)
    mesh = Mesh(np.array(jax.devices()), ("batch",))
    info = {}
    status, fail_at, n = check_batch(batch, F=64, mesh=mesh, info=info)
    assert all(s == LJ.VALID for s in status)
    # the mesh path must ride a fast engine, not the 20x-slower vmap
    # fallback (round-1 Weak #2)
    assert info["engine"] == "keys-sharded", info


def test_sharded_engines_match_solo():
    """Sharded keys/flat runs (8-device CPU mesh, B not divisible by
    the axis) must return the same verdicts and fail indices as the
    single-device engines on mixed valid/invalid/info histories."""
    import jax
    from jax.sharding import Mesh
    from comdb2_tpu.checker.batch import pack_batch, check_batch

    model = M.cas_register()
    histories = []
    for seed in range(13):          # 13 % 8 != 0: exercises padding
        rng = random.Random(72_000 + seed)
        h = histgen.register_history(
            rng, n_procs=rng.randint(2, 4),
            n_events=rng.randint(6, 28),
            p_info=0.1 if seed % 3 == 0 else 0.0)
        if seed % 2:
            h = histgen.mutate(rng, h)
        histories.append(h)
    batch = pack_batch(histories, model)
    mesh = Mesh(np.array(jax.devices()), ("batch",))
    solo_status, solo_fail, solo_n = check_batch(batch, F=64,
                                                 engine="keys")
    for engine in ("keys", "flat"):
        info = {}
        status, fail_at, n = check_batch(batch, F=64, mesh=mesh,
                                         engine=engine, info=info)
        assert info["engine"] == f"{engine}-sharded", info
        assert status.shape == (13,)
        assert list(status) == list(solo_status), (engine, status)
        assert list(fail_at) == list(solo_fail), (engine, fail_at)
        assert list(n) == list(solo_n), (engine, n)


def test_dedup_survives_sentinel_collisions():
    """Regression: hash-fingerprint dedup collided on rows swapping 0 and
    LIN(-2) across slots, interleaving equal rows and ballooning the
    frontier into spurious overflow. Exact-sort dedup must agree with the
    host engine at the host's true peak frontier size."""
    rng = random.Random(7)
    h = histgen.register_history(rng, n_procs=4, n_events=64, p_info=0.0)
    packed = pack_history(h)
    mm = make_memo(M.cas_register(), packed)
    r = linear_host.check(mm, packed)
    assert r.valid is True
    F = _next_pow2(r.max_frontier)  # tightest power-of-two capacity
    stream = LJ.make_stream(packed)
    status, fail_at, n = LJ.check_device(LJ.pad_succ(mm.succ), *stream,
                                         F=F, P=4)
    assert int(status) == LJ.VALID


def test_chunked_inplace_escalation_matches_monolithic(monkeypatch):
    """Large histories run the chunked engine with IN-PLACE capacity
    escalation: an overflow widens the boundary carry and re-runs only
    the overflowing chunk (a restart would repay every checked chunk
    per ladder level). Forced on via the threshold; verdicts must
    match the monolithic ladder on valid, invalid, and genuinely
    overflowing histories."""
    import random

    from comdb2_tpu.checker import linear
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops.op import Op
    from comdb2_tpu.ops.synth import register_history

    rng = random.Random(8)
    valid_h = register_history(rng, n_procs=4, n_events=600, values=4,
                               p_info=0.05)
    invalid_h = list(valid_h)
    for i in range(len(invalid_h) - 1, -1, -1):
        if invalid_h[i].type == "ok" and invalid_h[i].f == "read":
            invalid_h[i] = invalid_h[i].with_(value=99)
            break
    # frontier needs > 8 configs early on (3 pending writers), so the
    # first capacity level must overflow and escalate mid-history
    caps = (8, 256)

    orig_threshold = linear.CHUNKED_S_THRESHOLD
    for h in (valid_h, invalid_h):
        mono = linear.analysis(cas_register(), h, backend="device",
                               capacities=caps)
        monkeypatch.setattr(linear, "CHUNKED_S_THRESHOLD", 4)
        chunked = linear.analysis(cas_register(), h, backend="device",
                                  capacities=caps)
        monkeypatch.setattr(linear, "CHUNKED_S_THRESHOLD",
                            orig_threshold)
        assert chunked.valid == mono.valid, (chunked.info, mono.info)
        if not chunked.valid:
            assert chunked.op_index == mono.op_index

    # exhausted ladder still yields UNKNOWN: many concurrent pending
    # writers blow past the last capacity
    hard = []
    for p in range(10):
        hard.append(Op(process=p, type="invoke", f="write", value=p,
                       time=p))
    hard.append(Op(process=11, type="invoke", f="read", value=None,
                   time=20))
    hard.append(Op(process=11, type="ok", f="read", value=3, time=21))
    monkeypatch.setattr(linear, "CHUNKED_S_THRESHOLD", 4)
    a = linear.analysis(cas_register(), hard, backend="device",
                        capacities=(8, 16))
    assert a.valid == "unknown", a.info


# --- wide-P multi-word packed dedup (round-3 VERDICT #1) --------------------

def test_make_pack_plan_widths():
    """W grows with P; the top word leaves bits 29/30 for flags; every
    field fits its word."""
    for n_states, n_tr, P in ((6, 28, 18), (6, 28, 24), (6, 28, 32),
                              (16, 16, 5), (1 << 20, 4, 3)):
        plan = LJ.make_pack_plan(n_states, n_tr, P)
        assert plan is not None
        used = [0] * plan.n_words
        widths = [plan.state_bits] + [plan.slot_bits] * P
        for w_, (word, shift) in zip(widths, plan.assign):
            assert shift + w_ <= 31
            used[word] = max(used[word], shift + w_)
        assert used[-1] <= 29          # flag space in the top word
    # a single field wider than 29 bits can't pack
    assert LJ.make_pack_plan(1 << 30, 4, 2) is None


def test_pack_plan_words_injective():
    """Distinct configs must map to distinct word tuples at every P the
    plan accepts — including P far beyond the two-word budget."""
    import jax.numpy as jnp

    rng = random.Random(11)
    for n_states, n_tr, P in ((6, 28, 18), (6, 28, 32), (50, 100, 24)):
        plan = LJ.make_pack_plan(n_states, n_tr, P)
        assert plan is not None
        assert not LJ.pack_bits(n_states, n_tr, P)[2]   # 2 words can't
        seen = set()
        configs = []
        for _ in range(300):
            c = (rng.randrange(n_states),
                 tuple(rng.randrange(-2, n_tr) for _ in range(P)))
            if c not in seen:
                seen.add(c)
                configs.append(c)
        states = jnp.asarray([c[0] for c in configs], jnp.int32)
        slots = jnp.asarray([c[1] for c in configs], jnp.int32)
        words = LJ._pack_plan_words(states, slots, plan)
        packed = set(zip(*(np.asarray(w).tolist() for w in words)))
        assert len(packed) == len(configs)


@pytest.mark.parametrize("P", [18, 24, 32])
def test_wide_p_device_matches_host(P):
    """Concurrency far beyond the 62-bit key budget: the multi-word
    packed dedup must agree with the host engine (valid, invalid, and
    fail index). The reference has no width limit at all
    (knossos/linear/config.clj:157-295; CLI default concurrency 30,
    cli.clj:52-91)."""
    model = M.cas_register()
    rng = random.Random(4200 + P)
    h = histgen.register_history(rng, n_procs=P, n_events=140,
                                 values=4, p_info=0.0, max_pending=6)
    for variant in (h, histgen.mutate(rng, h)):
        packed = pack_history(variant)
        mm = make_memo(model, packed)
        r = linear_host.check(mm, packed, max_configs=1 << 20)
        a = analysis(model, packed, backend="device",
                     capacities=(512, 2048))
        assert a.info.get("backend") == "device"
        assert a.valid == r.valid, (P, a.valid, r.valid)
        if r.valid is False:
            assert a.op_index == r.op_index
