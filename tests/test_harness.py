"""Harness runtime tests: worker loops, nemesis, process recycling,
store round-trips, full runs against the atom SUT."""

import os

import pytest

from comdb2_tpu.checker import checkers as C
from comdb2_tpu.harness import cli, core, fake, store
from comdb2_tpu.harness import client as client_ns
from comdb2_tpu.harness import generator as G
from comdb2_tpu.models import model as M
from comdb2_tpu.ops.op import Op


def _base_test(tmp_path, **kw):
    t = fake.noop_test()
    state = fake.Atom()
    t.update({
        "nodes": [],
        "concurrency": 4,
        "db": fake.atom_db(state),
        "client": fake.atom_client(state),
        "model": M.cas_register(),
        "store-root": str(tmp_path / "store"),
        "name": "atom-test",
    })
    t.update(kw)
    return t


def test_noop_run(tmp_path):
    t = fake.noop_test()
    t["store-root"] = str(tmp_path / "store")
    t["nodes"] = []
    result = core.run(t)
    assert result["results"]["valid?"] is True
    assert result["history"] == []


def test_full_run_against_atom_sut(tmp_path):
    t = _base_test(tmp_path,
                   generator=G.clients(G.limit(60, G.cas_gen)))
    result = core.run(t)
    assert result["results"]["valid?"] is True
    h = result["history"]
    assert len(h) >= 120                      # invokes + completions
    assert {op.type for op in h} <= {"invoke", "ok", "fail"}
    # single-threaded process discipline: invoke/completion alternate
    pending = set()
    for op in h:
        if op.type == "invoke":
            assert op.process not in pending
            pending.add(op.process)
        else:
            assert op.process in pending
            pending.remove(op.process)


def test_worker_recycles_process_on_crash(tmp_path):
    class CrashyClient(client_ns.Client):
        def __init__(self):
            self.n = 0

        def setup(self, test, node):
            return self

        def invoke(self, test, op):
            self.n += 1
            if self.n == 2:
                raise RuntimeError("network exploded")
            return {**op, "type": "ok"}

    t = _base_test(tmp_path, concurrency=1,
                   client=CrashyClient(),
                   generator=G.clients(G.limit(3, {"type": "invoke",
                                                   "f": "read",
                                                   "value": None})),
                   checker=C.unbridled_optimism)
    result = core.run(t)
    h = result["history"]
    infos = [op for op in h if op.type == "info"]
    assert len(infos) == 1
    assert "indeterminate" in infos[0].extra.get("error", "")
    # the crashed op's process never appears again; successor is p+concurrency
    crashed_p = infos[0].process
    procs_after = {op.process for op in h[h.index(infos[0]) + 1:]}
    assert crashed_p not in procs_after
    assert crashed_p + 1 in procs_after


def test_nemesis_worker_runs(tmp_path):
    events = []

    class Nem(client_ns.Client):
        def invoke(self, test, op):
            events.append(op["f"])
            return dict(op)

    t = _base_test(tmp_path,
                   nemesis=Nem(),
                   generator=G.nemesis(
                       G.seq([{"type": "info", "f": "start"},
                              {"type": "info", "f": "stop"}]),
                       G.limit(10, G.cas_gen)))
    result = core.run(t)
    assert events == ["start", "stop"]
    nem_ops = [op for op in result["history"] if op.process == "nemesis"]
    assert len(nem_ops) == 4          # 2 invocations + 2 completions
    assert result["results"]["valid?"] is True


def test_invalid_history_detected(tmp_path):
    class LyingClient(client_ns.Client):
        def invoke(self, test, op):
            if op["f"] == "read":
                return {**op, "type": "ok", "value": 42}
            return {**op, "type": "ok"}

    # at least one read must occur or the lying client goes unnoticed —
    # seq the guaranteed ops, then pad with a random mix
    t = _base_test(tmp_path, concurrency=2,
                   client=LyingClient(),
                   generator=G.clients(G.seq(
                       [{"type": "invoke", "f": "write", "value": 1},
                        {"type": "invoke", "f": "read", "value": None},
                        G.limit(6, G.mix(
                            [{"type": "invoke", "f": "write", "value": 1},
                             {"type": "invoke", "f": "read",
                              "value": None}]))])))
    result = core.run(t)
    assert result["results"]["valid?"] is False


def test_store_round_trip(tmp_path):
    t = _base_test(tmp_path,
                   generator=G.clients(G.limit(20, G.cas_gen)))
    result = core.run(t)
    assert os.path.exists(store.path(result, "test.edn"))
    assert os.path.exists(store.path(result, "history.edn"))
    assert os.path.exists(store.path(result, "results.edn"))
    assert os.path.exists(store.path(result, "jepsen.log"))

    loaded = store.load("atom-test", result["start-time"],
                        store_root=result["store-root"])
    assert len(loaded["history"]) == len(result["history"])
    # offline re-check from the persisted artifact (store.clj:159-165)
    recheck = C.linearizable.check(loaded, M.cas_register(),
                                   loaded["history"])
    assert recheck["valid?"] is True
    # latest symlink
    lat = store.latest("atom-test", store_root=result["store-root"])
    assert lat is not None and lat["start-time"] == result["start-time"]


def test_cli_single_test_cmd(tmp_path):
    def test_fn(opts):
        state = fake.Atom()
        t = fake.noop_test()
        t.update({
            "nodes": opts["nodes"],
            "concurrency": opts["concurrency"],
            "db": fake.atom_db(state),
            "client": fake.atom_client(state),
            "model": M.cas_register(),
            "generator": G.clients(G.limit(10, G.cas_gen)),
            "store-root": opts["store-root"],
            "name": "cli-test",
        })
        return t

    rc = cli.single_test_cmd(
        test_fn, argv=["--concurrency", "2",
                       "--store-root", str(tmp_path / "store")])
    assert rc == 0


def test_phases_barrier_works_inside_worker_threads(tmp_path):
    """The canonical set workload: concurrent adds, then one final read.
    gen.phases must hold the read back until every add thread finishes —
    this only works if *threads* is bound inside each worker thread."""
    added = []
    state_lock = __import__("threading").Lock()

    class SetClient(client_ns.Client):
        def invoke(self, test, op):
            if op["f"] == "add":
                import time
                time.sleep(0.01)
                with state_lock:
                    added.append(op["value"])
                return {**op, "type": "ok"}
            with state_lock:
                return {**op, "type": "ok", "value": frozenset(added)}

    counter = iter(range(10**6))
    adds = G.limit(24, lambda t, p: {"type": "invoke", "f": "add",
                                     "value": next(counter)})
    final_read = G.once({"type": "invoke", "f": "read", "value": None})
    t = _base_test(tmp_path, concurrency=4,
                   client=SetClient(),
                   checker=C.set_checker,
                   generator=G.clients(G.phases(adds, final_read)))
    result = core.run(t)
    assert result["results"]["valid?"] is True, result["results"]
    assert result["results"]["lost"] == "#{}"


def test_cli_invalid_dominates_unknown(tmp_path, monkeypatch):
    verdicts = iter(["unknown", False])

    def fake_run(test):
        return {"results": {"valid?": next(verdicts)}}

    monkeypatch.setattr(core, "run", fake_run)
    rc = cli.single_test_cmd(lambda opts: {}, argv=["--test-count", "2"])
    assert rc == 1


def test_snarf_logs_downloads_per_node(tmp_path):
    from comdb2_tpu.control.remote import RecordingRemote
    from comdb2_tpu.harness import db as db_ns
    from comdb2_tpu.harness import generator as G
    from comdb2_tpu.models import model as M

    class LoggedDB(db_ns.DB, db_ns.LogFiles):
        def log_files(self, test, node):
            return [f"/var/log/sut/{node}.log"]

    rec = RecordingRemote()
    state = fake.Atom()
    t = fake.noop_test()
    t.update({"nodes": ["n1", "n2"], "concurrency": 2,
              "name": "snarf", "store-root": str(tmp_path / "store"),
              "remote": rec, "db": LoggedDB(),
              "client": fake.atom_client(state),
              "model": M.cas_register(),
              "generator": G.clients(G.limit(4, G.cas_gen))})
    result = core.run(t)
    assert result["results"]["valid?"] is True
    assert sorted(rec.downloads) == [
        ("n1", "/var/log/sut/n1.log",
         store.path(result, "n1", "var/log/sut/n1.log")),
        ("n2", "/var/log/sut/n2.log",
         store.path(result, "n2", "var/log/sut/n2.log"))]


def test_independent_checker_writes_per_key_artifacts(tmp_path):
    from comdb2_tpu.checker import checkers as C
    from comdb2_tpu.checker import independent as I
    from comdb2_tpu.models import model as M
    from comdb2_tpu.ops.kv import tuple_
    from comdb2_tpu.ops.op import invoke, ok

    h = []
    for k in range(3):
        h += [invoke(k, "write", tuple_(k, 1)),
              ok(k, "write", tuple_(k, 1))]
    c = I.checker(C.Linearizable())
    test = {"name": "ind", "dir": str(tmp_path)}
    r = c.check(test, M.register(), h)
    assert r["valid?"] is True
    for k in range(3):
        assert (tmp_path / "independent" / str(k) / "results.edn").exists()
        assert (tmp_path / "independent" / str(k) / "history.edn").exists()


def test_on_nodes_parallel_and_errors():
    calls = []

    def good(test, node):
        calls.append(node)

    core._on_nodes({"nodes": ["a", "b", "c"]}, good)
    assert sorted(calls) == ["a", "b", "c"]

    def bad(test, node):
        raise ValueError(node)

    with pytest.raises(ValueError):
        core._on_nodes({"nodes": ["a"]}, bad)
