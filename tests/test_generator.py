"""Generator DSL tests (jepsen/generator.clj semantics)."""

import threading
import time

from comdb2_tpu.harness import generator as G

TEST = {"concurrency": 4, "nodes": ["a", "b"]}


def test_constant_generators():
    # any object is a constant generator of itself; None terminates
    assert G.op({"type": "invoke", "f": "read"}, TEST, 0)["f"] == "read"
    assert G.op(None, TEST, 0) is None
    assert G.op(G.void, TEST, 0) is None


def test_fn_generator():
    assert G.op(lambda t, p: {"f": p}, TEST, 3)["f"] == 3
    assert G.op(lambda: {"f": "x"}, TEST, 0)["f"] == "x"


def test_process_to_thread_and_node():
    assert G.process_to_thread(TEST, 6) == 2      # 6 mod 4
    assert G.process_to_thread(TEST, "nemesis") == "nemesis"
    assert G.process_to_node(TEST, 5) == "b"      # thread 1 -> nodes[1]
    assert G.process_to_node(TEST, "nemesis") is None


def test_once():
    g = G.once({"f": "x"})
    assert G.op(g, TEST, 0) == {"f": "x"}
    assert G.op(g, TEST, 1) is None


def test_seq_moves_past_exhausted():
    g = G.seq([G.void, {"f": "a"}, {"f": "b"}])
    # constant generators repeat forever, so seq sticks on "a" until
    # asked again... no: seq draws one op per element then advances
    assert G.op(g, TEST, 0)["f"] == "a"
    assert G.op(g, TEST, 0)["f"] == "b"
    assert G.op(g, TEST, 0) is None


def test_limit():
    g = G.limit(2, {"f": "x"})
    assert G.op(g, TEST, 0) is not None
    assert G.op(g, TEST, 0) is not None
    assert G.op(g, TEST, 0) is None


def test_time_limit():
    g = G.time_limit(0.05, {"f": "x"})
    assert G.op(g, TEST, 0) is not None
    time.sleep(0.08)
    assert G.op(g, TEST, 0) is None


def test_mix_uniform():
    g = G.mix([{"f": "a"}, {"f": "b"}])
    seen = {G.op(g, TEST, 0)["f"] for _ in range(50)}
    assert seen == {"a", "b"}


def test_filter():
    src = G.seq([{"f": "a"}, {"f": "b"}, {"f": "a"}])
    g = G.filter_gen(lambda o: o["f"] == "a", src)
    assert G.op(g, TEST, 0)["f"] == "a"
    assert G.op(g, TEST, 0)["f"] == "a"   # skips b
    assert G.op(g, TEST, 0) is None


def test_on_routes_by_thread():
    g = G.on(lambda t: t == G.NEMESIS, {"f": "boom"})
    with G.with_threads([G.NEMESIS, 0, 1, 2, 3]):
        assert G.op(g, TEST, "nemesis")["f"] == "boom"
        assert G.op(g, TEST, 0) is None
        assert G.op(g, TEST, 5) is None


def test_nemesis_and_clients_split():
    g = G.nemesis({"f": "n"}, {"f": "c"})
    with G.with_threads([G.NEMESIS, 0, 1, 2, 3]):
        assert G.op(g, TEST, "nemesis")["f"] == "n"
        assert G.op(g, TEST, 2)["f"] == "c"
    gc = G.clients({"f": "c"})
    with G.with_threads([G.NEMESIS, 0, 1, 2, 3]):
        assert G.op(gc, TEST, "nemesis") is None
        assert G.op(gc, TEST, 1)["f"] == "c"


def test_reserve_partitions_threads():
    g = G.reserve(2, {"f": "w"}, 1, {"f": "c"}, {"f": "r"})
    with G.with_threads([0, 1, 2, 3]):
        assert G.op(g, TEST, 0)["f"] == "w"
        assert G.op(g, TEST, 1)["f"] == "w"
        assert G.op(g, TEST, 2)["f"] == "c"
        assert G.op(g, TEST, 3)["f"] == "r"


def test_concat_first_non_nil():
    g = G.concat(G.void, {"f": "x"})
    assert G.op(g, TEST, 0)["f"] == "x"


def test_each_per_process():
    g = G.each(lambda: G.limit(1, {"f": "x"}))
    assert G.op(g, TEST, 0) is not None
    assert G.op(g, TEST, 1) is not None   # fresh copy for process 1
    assert G.op(g, TEST, 0) is None       # process 0's copy exhausted


def test_queue_gen_and_drain():
    g = G.drain_queue(G.limit(20, G.queue_gen()))
    enq = deq = 0
    while True:
        o = G.op(g, TEST, 0)
        if o is None:
            break
        if o["f"] == "enqueue":
            enq += 1
        else:
            deq += 1
    assert deq >= enq


def test_synchronize_barrier():
    g = G.synchronize({"f": "x"})
    results = []
    def draw():
        with G.with_threads([0, 1]):
            results.append(G.op(g, {"concurrency": 2}, 0))
    t1 = threading.Thread(target=draw)
    t1.start()
    time.sleep(0.05)
    assert not results           # blocked on the barrier
    t2 = threading.Thread(target=draw)
    t2.start()
    t1.join(2)
    t2.join(2)
    assert len(results) == 2


def test_phases_orders_generators():
    g = G.phases(G.limit(1, {"f": "a"}), G.limit(1, {"f": "b"}))
    with G.with_threads([0]):
        assert G.op(g, {"concurrency": 1}, 0)["f"] == "a"
        assert G.op(g, {"concurrency": 1}, 0)["f"] == "b"
        assert G.op(g, {"concurrency": 1}, 0) is None


def test_stagger_and_sleep_timing():
    t0 = time.monotonic()
    assert G.op(G.sleep(0.03), TEST, 0) is None
    assert time.monotonic() - t0 >= 0.03


def test_delay_til_ticks():
    g = G.delay_til(0.02, {"f": "x"})
    t0 = time.monotonic()
    G.op(g, TEST, 0)
    G.op(g, TEST, 0)
    # two draws land on two distinct ticks ~0.02s apart
    assert time.monotonic() - t0 >= 0.02


def test_start_stop():
    g = G.start_stop(0, 0)
    assert G.op(g, TEST, 0)["f"] == "start"
    assert G.op(g, TEST, 0)["f"] == "stop"
    assert G.op(g, TEST, 0) is None
