"""MXU frontier engine (checker/mxu) — BFS-as-matmul for wide P.

Contracts:

- bit-exact verdict parity with the host oracle and the XLA seg
  engine on overlapping (P <= 15) shapes;
- a genuinely concurrent wide-P bounded-in-flight history that
  overflows the XLA engine's frontier cap gets a DEFINITE verdict
  from the MXU engine (the scaled tier-1 proxy of the bench's
  65536 -> 131072 crossing);
- in-place capacity escalation (``expand_carry``) resumes at the
  overflowing chunk and reproduces the single-dispatch verdict;
- the driver ladder routes wide P to the engine (``engine ==
  "mxu-frontier"``) and the batch path auto-picks it;
- UNKNOWN artifacts name the engine + capacity that gave up
  (``cause`` / ``engines_tried`` — the round-10 attribution fix);
- observed lowerings stay inside the PROGRAMS.md inventory.
"""

import random

import numpy as np
import pytest

from comdb2_tpu.checker import analysis
from comdb2_tpu.checker import linear_host, linear_jax as LJ
from comdb2_tpu.checker import mxu as MXU
from comdb2_tpu.checker.linear import _next_pow2
from comdb2_tpu.models.memo import memo as make_memo
from comdb2_tpu.models import model as M
from comdb2_tpu.ops import synth_columnar as SC
from comdb2_tpu.ops.packed import pack_history

import histgen


def _prep(model, h, s_pad=32, k_pad=4):
    """pack -> memo -> bucketed, slot-renamed segments (the driver's
    shape discipline, so the suite shares a few compiled programs)."""
    packed = h if not isinstance(h, list) else pack_history(h)
    mm = make_memo(model, packed)
    segs = LJ.make_segments(packed, s_pad=s_pad, k_pad=k_pad)
    segs, p_eff = LJ.remap_slots(segs)
    succ = LJ.pad_succ(mm.succ, _next_pow2(mm.n_states),
                       _next_pow2(mm.n_transitions))
    return packed, mm, segs, succ, max(p_eff, 1)


def _mxu(mm, segs, succ, P, F=128):
    st, fa, n = MXU.check_device_mxu(
        succ, segs.inv_proc, segs.inv_tr, segs.ok_proc, segs.depth,
        F=F, P=P, n_states=mm.n_states, n_transitions=mm.n_transitions)
    return int(st), int(fa), int(n)


# --- parity on overlapping P <= 15 shapes ----------------------------------

@pytest.mark.parametrize("seed", range(25))
def test_matches_host_and_xla_random(seed):
    """Verdict + fail-index + final-count parity against the host
    oracle AND the XLA seg engine on small register histories (the
    engines must be bit-exact, not merely verdict-equal)."""
    rng = random.Random(88_000 + seed)
    h = histgen.register_history(rng, n_procs=rng.randint(2, 4),
                                 n_events=rng.randint(6, 24),
                                 p_info=0.1)
    if rng.random() < 0.6:
        h = histgen.mutate(rng, h)
    packed, mm, segs, succ, P = _prep(M.cas_register(), h)
    st, fa, n = _mxu(mm, segs, succ, P)
    st2, fa2, n2 = LJ.check_device_seg(
        succ, segs.inv_proc, segs.inv_tr, segs.ok_proc, segs.depth,
        F=128, P=P, n_states=mm.n_states,
        n_transitions=mm.n_transitions)
    # the cross-engine contract (CLAUDE.md): counts compare on VALID
    # verdicts only — on INVALID the seg engine zeroes its count while
    # the flat-layout engines (mxu included) keep the pre-death one
    assert st == int(st2)
    if st == LJ.VALID:
        assert n == int(n2)
    else:
        assert fa == int(fa2)
    hr = linear_host.check(mm, packed)
    assert st in (LJ.VALID, LJ.INVALID)
    assert (st == LJ.VALID) == hr.valid
    if st == LJ.INVALID:
        assert int(segs.seg_index[fa]) == hr.op_index


def test_wide_p_generator_parity_small():
    """The wave generator's valid + violation twins, cross-checked
    against the host oracle at P = 16 (small free-read count keeps the
    frontier tiny)."""
    for violation in (False, True):
        ps = SC.wide_register_batch_packed(
            31, 2, n_waves=2, n_chain=12, n_free=4, values=16,
            violation=violation)
        for p in ps:
            packed, mm, segs, succ, P = _prep(M.cas_register(), p)
            assert P == 16          # genuinely concurrent: P_eff = P
            st, fa, _ = _mxu(mm, segs, succ, P, F=1024)
            hr = linear_host.check(mm, packed)
            assert hr.valid is (not violation)
            assert (st == LJ.VALID) == hr.valid
            if st == LJ.INVALID:
                assert int(segs.seg_index[fa]) == hr.op_index


def test_wide_p_generator_rejects_unseedable_violation():
    """``violation=True`` needs a free read to seed — with n_free=0
    the twin would silently be a valid history (a harness's
    'violation => INVALID' assertion would then fail far from the
    cause), so the constructor refuses."""
    with pytest.raises(ValueError, match="n_free >= 1"):
        SC.wide_register_batch_columns(31, 1, n_waves=2, n_chain=16,
                                       n_free=0, values=18,
                                       violation=True)


# --- the workload-class conversion: XLA cap overflow -> MXU verdict --------

def _wide_case(n_free=9, violation=False):
    ps = SC.wide_register_batch_packed(
        47, 1, n_waves=2, n_chain=7, n_free=n_free, values=16,
        violation=violation)
    return _prep(M.cas_register(), ps[0], s_pad=64, k_pad=4)


def test_wide_p_unknown_becomes_verdict():
    """A P=16 bounded-in-flight history whose free-read subset
    frontier (2^9 + chain) overflows the XLA engine at its capacity
    rung gets a DEFINITE verdict from the MXU engine at the next rung
    — the scaled proxy of the bench's 65536 -> 131072 crossing."""
    packed, mm, segs, succ, P = _wide_case()
    assert P == 16
    st_x, _, _ = LJ.check_device_seg(
        succ, segs.inv_proc, segs.inv_tr, segs.ok_proc, segs.depth,
        F=256, P=P, n_states=mm.n_states,
        n_transitions=mm.n_transitions)
    assert int(st_x) == LJ.UNKNOWN      # 2^9 free-read subsets > 256
    st, _, n = _mxu(mm, segs, succ, P, F=1024)
    assert st == LJ.VALID and n >= 1
    # and the violation twin dies with a definite INVALID, not UNKNOWN
    packed, mm, segs, succ, P = _wide_case(violation=True)
    st, fa, _ = _mxu(mm, segs, succ, P, F=1024)
    assert st == LJ.INVALID
    hr = linear_host.check(mm, packed)
    assert int(segs.seg_index[fa]) == hr.op_index


def test_chunked_expand_carry_escalates_in_place():
    """The chunk form resumes from a widened PRE-chunk carry: F=64
    overflows, expand_carry(1024) re-runs only the chunk, and the
    verdict matches the single-dispatch engine."""
    packed, mm, segs, succ, P = _wide_case()
    sizes = dict(n_states=mm.n_states, n_transitions=mm.n_transitions)
    want = _mxu(mm, segs, succ, P, F=1024)
    S = segs.ok_proc.shape[0]
    chunk = 32
    F = 64
    carry = MXU.init_carry(1, F, P, **sizes)
    done = 0
    escalated = False
    while done < S:
        end = done + chunk
        new_carry = MXU.check_device_mxu_chunk(
            succ, segs.inv_proc[done:end], segs.inv_tr[done:end],
            segs.ok_proc[done:end], segs.depth[done:end], done,
            carry, F=F, P=P, **sizes)
        if int(new_carry[3][0]) == LJ.UNKNOWN and F < 1024:
            F = 1024
            carry = MXU.expand_carry(carry, F)
            escalated = True
            continue                # same chunk, wider frontier
        carry = new_carry
        done = end
        if int(carry[3][0]) != LJ.VALID:
            break
    assert escalated, "the F=64 rung should have overflowed"
    got = (int(carry[3][0]), int(carry[4][0]), int(carry[2][0]))
    assert got == want


def test_driver_routes_wide_p_to_mxu():
    """End to end through ``analysis``: wide-P valid/violation twins
    ride the MXU arm, with engine attribution in the artifact."""
    for violation in (False, True):
        ps = SC.wide_register_batch_packed(
            53, 1, n_waves=2, n_chain=13, n_free=3, values=16,
            violation=violation)
        a = analysis(M.cas_register(), ps[0], backend="device",
                     host_threshold=1)
        assert a.info["engine"] == "mxu-frontier"
        assert a.info["frontier_capacity"] in MXU.CAPACITIES
        assert a.valid is (not violation)


def test_driver_chunked_progress_and_histogram():
    """The chunked driver arm (forced by a progress callback) must
    reproduce the non-chunked verdict and report telemetry through
    the MXU pending histogram."""
    ps = SC.wide_register_batch_packed(59, 1, n_waves=3, n_chain=14,
                                       n_free=2, values=17)
    ticks = []

    def progress(done, total, count, stats):
        ticks.append((done, total, count, stats))

    a = analysis(M.cas_register(), ps[0], backend="device",
                 host_threshold=1, progress=progress,
                 progress_interval_s=0.0)
    assert a.valid is True
    assert a.info["engine"] == "mxu-frontier"
    assert ticks and all(t[1] >= t[0] > 0 for t in ticks)
    assert all("est_cost" in t[3] for t in ticks)


def test_unknown_artifact_names_engine_and_capacity(monkeypatch):
    """The attribution fix: a capacity give-up must say WHICH engine
    overflowed at WHAT capacity — a wide-P UNKNOWN and an XLA
    capacity abort used to render identically."""
    monkeypatch.setattr(MXU, "CAPACITIES", (64,))
    ps = SC.wide_register_batch_packed(61, 1, n_waves=2, n_chain=8,
                                       n_free=8, values=16)
    a = analysis(M.cas_register(), ps[0], backend="device",
                 host_threshold=1)
    assert a.valid == "unknown"
    assert "mxu-frontier" in a.info["cause"]
    assert "64" in a.info["cause"]
    # the XLA arm attributes the same way (narrow P, tiny ladder)
    h = []
    import comdb2_tpu.ops.op as O
    for i in range(8):
        h.append(O.invoke(i, "write", i))
        h.append(O.info(i, "write", i))
    h += [O.invoke(100, "read", None), O.ok(100, "read", 5)]
    a2 = analysis(M.register(), h, backend="device",
                  host_threshold=1, capacities=(16,))
    assert a2.valid == "unknown"
    assert "xla-seg2" in a2.info["cause"]


def test_capacities_bounds_mxu_ladder(monkeypatch):
    """``analysis(capacities=...)`` bounds the MXU arm too: each entry
    buckets up to the engine's declared rungs and the ladder stops at
    the caller's bound — a caller limiting device work can force an
    early UNKNOWN instead of silently escalating to the top rung."""
    monkeypatch.setattr(MXU, "CAPACITIES", (64, 256))
    ps = SC.wide_register_batch_packed(61, 1, n_waves=2, n_chain=9,
                                       n_free=7, values=16)
    # peak frontier ~ n_chain + 2^n_free = 137: past 64, inside 256.
    # A 16-bound buckets to the 64 rung ONLY — overflow there is final
    a = analysis(M.cas_register(), ps[0], backend="device",
                 host_threshold=1, capacities=(16,))
    assert a.valid == "unknown"
    assert a.info["engine"] == "mxu-frontier"
    assert "64" in a.info["cause"]
    # a bound that buckets onto the wider rung gets the verdict there
    a2 = analysis(M.cas_register(), ps[0], backend="device",
                  host_threshold=1, capacities=(16, 100))
    assert a2.valid is True
    assert a2.info["frontier_capacity"] == 256


# --- gating ----------------------------------------------------------------

def test_serves_gating(monkeypatch):
    assert MXU.serves(32, 32, 16)
    assert not MXU.serves(32, 32, 15)        # fused-kernel territory
    assert not MXU.serves(512, 32, 16)       # past S_CAP
    assert not MXU.serves(32, 256, 16)       # past T_CAP
    assert not MXU.serves(32, 32, MXU.MAX_P + 1)
    assert MXU.fits(32, 32, 4)               # fits() has no P floor:
    monkeypatch.setenv("COMDB2_TPU_MXU", "0")  # parity paths use it
    assert not MXU.serves(32, 32, 16)


def test_env_kill_switch_routes_back_to_xla(monkeypatch):
    monkeypatch.setenv("COMDB2_TPU_MXU", "0")
    ps = SC.wide_register_batch_packed(53, 1, n_waves=2, n_chain=13,
                                       n_free=3, values=16)
    a = analysis(M.cas_register(), ps[0], backend="device",
                 host_threshold=1)
    assert a.valid is True
    assert a.info["engine"] == "xla-seg2"


# --- batch path ------------------------------------------------------------

def test_batch_auto_picks_mxu_and_matches_driver():
    from comdb2_tpu.checker.batch import check_batch, pack_batch

    ps = SC.wide_register_batch_packed(67, 3, n_waves=2, n_chain=14,
                                       n_free=2, values=17)
    bad = SC.wide_register_batch_packed(67, 1, n_waves=2, n_chain=14,
                                        n_free=2, values=17,
                                        violation=True)
    batch = pack_batch(ps + bad, M.cas_register(),
                       build_streams=False)
    info = {}
    st, fa, nf = check_batch(batch, F=1024, info=info)
    assert info["engine"] == "mxu"
    assert st.tolist() == [LJ.VALID] * 3 + [LJ.INVALID]
    # the INVALID lane's fail index matches the host oracle
    mm = make_memo(M.cas_register(), bad[0])
    hr = linear_host.check(mm, bad[0])
    assert int(fa[3]) == hr.op_index


def test_batch_lowerings_stay_inside_inventory():
    """The runtime compile guard agrees with the static inventory on
    the REAL mxu lowerings (eval_shape witnesses alone can drift)."""
    from comdb2_tpu.analysis.compile_surface import static_inventory
    from comdb2_tpu.checker.batch import check_batch, pack_batch
    from comdb2_tpu.utils import compile_guard as CG

    ps = SC.wide_register_batch_packed(71, 2, n_waves=2, n_chain=14,
                                       n_free=2, values=17)
    batch = pack_batch(ps, M.cas_register(), build_streams=False)
    with CG.guard() as g:
        st, _, _ = check_batch(batch, F=1024, engine="mxu")
    assert st.tolist() == [LJ.VALID] * 2
    g.assert_closed(static_inventory())
