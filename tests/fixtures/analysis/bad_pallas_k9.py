"""Seeded violation: requesting a kernel spec at K=9. The fused
kernel caps K (invokes per segment) at 8; fault-window cluster
histories can exceed it and must take the XLA path instead."""

from comdb2_tpu.checker.pallas_seg import spec_for

SPEC = spec_for(8, 32, 3, 9)                  # <- pallas-k-cap
