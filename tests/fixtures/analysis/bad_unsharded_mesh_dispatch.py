"""Seeded violation: a shard_map dispatch site fed shapes not divided
from a declared bucket. The mesh sinks compile ONE per-shard program
per (B/D, table dims) class — a raw ``len(...)`` batch width or raw
memo counts make every distinct traffic shape a fresh per-shard
program, multiplied by the mesh size."""

from comdb2_tpu.checker import linear_jax as LJ


def check_mesh(mesh, memo, succ, sb, histories):
    # BUG: raw len(...) as the sharded batch width AND raw memo
    # counts as the table dims — nothing here is drawn from a pow2
    # ladder, so the shard-map body compiles per seed
    return LJ.check_device_keys_sharded(
        mesh, succ, sb.inv_proc, sb.inv_tr, sb.ok_proc, sb.depth,
        B=len(histories), F=128, P=4,
        n_states=memo.n_states, n_transitions=memo.n_transitions)
