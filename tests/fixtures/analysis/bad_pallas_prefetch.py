"""Seeded violation: a 2048x10 scalar-prefetch stream. Scalar-prefetch
SMEM holds ~14336 int32 (~56 KB) per kernel call; 2048x10 = 20480
words fails — chunk long segment streams."""

import numpy as np
from jax.experimental.pallas import tpu as pltpu


def make_stream(kernel_call):
    seg = np.zeros((2048, 10), np.int32)   # <- pallas-prefetch-smem
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(1024,), in_specs=[], out_specs=[])
    return kernel_call(grid_spec, seg)
