"""Seeded violation: raw (unbucketed) shapes reach the workload-family
jit boundary — the ``wl_bank_check``/``wl_dirty_check`` dispatch sinks
of the ``unbucketed-dispatch-site`` rule. The raw ``len(...)`` count is
laundered through a helper so only the interprocedural chase can tie
the call site to the family entry's static shape argument; one
compiled program per distinct history shape, recompiles can OOM LLVM.
"""

from comdb2_tpu.checker.wl import bank as WB
from comdb2_tpu.checker.wl import dirty as WD


def _dispatch_bank(cols, n_reads, n_accounts):
    # the sink: the bank entry's static dims come from the caller's
    # parameters
    return WB.wl_bank_check(
        cols.reads, cols.read_mask, cols.wrong_n, cols.init,
        cols.transfers, cols.total, n_reads=n_reads,
        n_accounts=n_accounts, n_snaps=8)


def check_all(batches):
    out = []
    for cols, reads in batches:
        # BUG: raw per-batch counts, no bucket_of — every distinct
        # history shape compiles a fresh program
        out.append(_dispatch_bank(cols, len(reads), len(cols.init)))
    return out


def check_dirty(cols, values):
    # BUG: the dirty value-universe width straight off the interning
    # table — one program per distinct alphabet
    return WD.wl_dirty_check(cols.failed, cols.reads, cols.node_mask,
                             cols.read_mask, n_reads=8, n_nodes=4,
                             n_values=len(values))
