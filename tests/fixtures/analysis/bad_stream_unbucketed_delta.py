"""Seeded violation: raw (unbucketed) shapes reach the streaming-
session delta entrypoint — the ``stream_delta_chunk`` dispatch sink
of the ``unbucketed-dispatch-site`` rule. A live history's alphabet
grows as traffic arrives; raw memo counts here compile one program
PER GROWTH STEP of every monitored session (the exact storm the
``stream.engine.pad_sizes`` pow2 buckets exist to prevent). The raw
``memo.n_states`` is laundered through a helper so only the
interprocedural chase can tie the call site to the static shape
argument."""

from comdb2_tpu.checker import linear_jax as LJ
from comdb2_tpu.stream.engine import stream_delta_chunk


def _dispatch_delta(succ, ip, it, okp, dp, off, carry, n_states,
                    n_transitions):
    # the sink: the session rung's jit entry with static table dims
    # taken from the caller's parameters
    return stream_delta_chunk(
        succ, ip, it, okp, dp, off, carry, F=256, Fs=32, P=4,
        n_states=n_states, n_transitions=n_transitions)


def append_all(session, deltas):
    carry = LJ.init_seg_carry(256, 4)
    for memo, (ip, it, okp, dp, off) in deltas:
        # BUG: raw memo counts, no pad_sizes/next_pow2 — every append
        # that grew the alphabet compiles a fresh program per session
        carry = _dispatch_delta(session.succ_dev, ip, it, okp, dp,
                                off, carry, memo.n_states,
                                memo.n_transitions)
    return carry
