"""Seeded violation: replay log appended before the guarded call
succeeded (rule ``log-after-success``).

The stream client's retained-delta log and ``IncrementalMemo``'s
extend log are REPLAYED on failover/restore: an entry recorded before
the send/extend succeeds makes every replay repeat the failure (or
double-apply a delta the server never acked)."""


def append(self, session, payload):
    seq = self._next_seq(session)
    self._delta_log.append((seq, payload))   # finding: log first
    self._send(session.node, seq, payload)
    return seq
