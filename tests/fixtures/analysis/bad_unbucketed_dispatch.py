"""Seeded violation: raw (unbucketed) shapes reach a batch jit
boundary — laundered through a helper function, so only the
INTERPROCEDURAL chase of the ``unbucketed-dispatch-site`` rule can
tie the raw ``memo.n_states`` at the call site to the engine entry's
shape argument. One compiled program per distinct history shape;
recompiles can OOM LLVM."""

from comdb2_tpu.checker import linear_jax as LJ
from comdb2_tpu.checker.batch import check_batch


def _dispatch(succ, sb, n_states, n_transitions):
    # the sink: a batched engine entry whose static shape args come
    # from the caller's parameters
    return LJ.check_device_seg_batch(
        succ, sb.inv_proc, sb.inv_tr, sb.ok_proc, sb.depth,
        F=128, P=4, n_states=n_states, n_transitions=n_transitions)


def check_all(batches):
    out = []
    for memo, sb in batches:
        # BUG: raw memo counts, no next_pow2 — every distinct history
        # shape compiles a fresh program
        out.append(_dispatch(memo.succ, sb, memo.n_states,
                             memo.n_transitions))
    return out


def check_one(batch, items):
    # BUG: a raw item count as the segment floor — same hazard,
    # provable without the call-graph chase
    return check_batch(batch, s_pad=len(items))
