"""Seeded violation: per-item device dispatch in a host loop. Each
dispatch pays the ~100 ms tunnel round-trip (1.5k ops/s serial vs 93k
streamed) — pack the items into one ``checker.batch.check_batch``
call or submit them to the ``comdb2_tpu.service`` verifier daemon."""

from comdb2_tpu.checker import linear_jax as LJ


def check_all(batches, succ):
    out = []
    for b in batches:
        out.append(LJ.check_device_batch(          # <- per-item-dispatch
            succ, b.kind, b.proc, b.tr, F=256, P=4,
            n_states=8, n_transitions=16))
    return out
