"""Seeded violation: host<->device transfer inside a per-item loop
(rule ``per-item-transfer``).

The data-movement twin of ``per-item-dispatch``: N carries pushed
through the tunnel one ``device_put`` at a time pay N ~100 ms round
trips (measured 1.5k vs 93k ops/s for the same work streamed). Batch
the items and ride ONE dispatch's jit transfer."""

import jax


def restore_all(self, snapshots):
    carries = []
    for snap in snapshots:
        carries.append(jax.device_put(snap))   # finding: per-item
    return carries
