"""Seeded violation: a 2048-step Pallas grid. SMEM is bounded per
grid step (~500 B/step toward the 1 MB space): a 2048-step grid fails
Mosaic compile ("Exceeded smem capacity") even at prefetch width 4,
while 1408 steps compile — keep the chunk at 1024."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def run(kernel, x):
    return pl.pallas_call(
        kernel,
        grid=(2048,),                         # <- pallas-grid-steps
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32),
    )(x)
