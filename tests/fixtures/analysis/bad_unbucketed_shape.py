"""Seeded violation: unbucketed shapes at a jit boundary. XLA
compiles one program per distinct input shape; per-seed shapes
recompile per seed and can OOM LLVM — pad sizes to the declared
buckets (pow2 pads, the fuzz bucket ladder)."""

from comdb2_tpu.checker import linear_jax as LJ


def check(packed, succ):
    bucket = (13, 37)                  # <- jaxpr-unbucketed-shape
    segs = LJ.make_segments(packed, s_pad=100, k_pad=8)   # <- and here
    return LJ.check_device_seg(
        succ, segs.inv_proc, segs.inv_tr, segs.ok_proc, segs.depth,
        F=128, P=4, n_states=bucket[0], n_transitions=bucket[1])
