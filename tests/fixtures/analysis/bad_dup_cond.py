"""Seeded violation: the same closure body inlined under two branches
of nested ``lax.cond`` — XLA compiles the body once per branch path
and CPU compile time explodes. Run the small tier unconditionally and
select with ONE cond."""

import jax.numpy as jnp
from jax import lax


def search_step(frontier, use_small, escalate):
    def small_tier(f):
        return jnp.sort(f.reshape(-1))[:128]

    def outer(f):
        return lax.cond(escalate,
                        lambda x: jnp.sort(x.reshape(-1))[:128],
                        lambda x: x[:128], f)

    return lax.cond(use_small,
                    lambda x: jnp.sort(x.reshape(-1))[:128],
                    outer, frontier)
