"""Seeded violation: session pin released outside ``try/finally`` on
a cleanup path (rule ``release-in-finally``).

A ``close`` that raises before its ``_unpin`` leaks the affinity pin
forever: failover never re-routes the session and idle eviction never
fires — the PR-12 failed-close pin leak, machine-checked."""


def close(self, session):
    out = self._finalize(session)        # may raise (rung re-route)
    self._unpin(session.key)             # finding: not in finally
    return out
