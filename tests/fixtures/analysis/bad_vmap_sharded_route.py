"""Seeded violation: production code routing mesh traffic onto the
vmap-sharded TEST ORACLE. ``linear_jax.check_sharded`` shard_maps the
vmap engine, which lowers ~20x worse per lane than the flat-batch
encodings — round 7 removed the last production route; serving
traffic goes through ``checker.batch.check_batch``'s stream/keys/flat
sharded engines."""


def serve_batch(mesh, succ, batch):
    from comdb2_tpu.checker.linear_jax import check_sharded

    # BUG: the oracle on the serving path
    return check_sharded(mesh, succ, batch.kind, batch.proc,
                         batch.tr, F=256, P=4)
