"""Seeded violation: a per-op Python loop over ``history.ops`` inside
a pack/segment module. The ingest path is columnar — per-op walks
measured ``host_pack_s = 278.2`` against ~70 s of device time at the
4096x bench shape; Op objects are API-edge views only."""

import numpy as np


def repack_transitions(history):
    trans = np.full(len(history.ops), -1, np.int32)
    table = {}
    for i, op in enumerate(history.ops):       # <- per-op-host-loop
        if op.type == "invoke" and not op.fails:
            trans[i] = table.setdefault((op.f, op.value), len(table))
    return trans
