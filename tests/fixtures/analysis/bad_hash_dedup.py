"""Seeded violation: hash-fingerprint dedup in an engine module.

Colliding non-identical rows can interleave between equal rows and
break sort adjacency — the frontier balloons into spurious overflow.
Dedup must be EXACT (sort rows by full contents, merge neighbours)."""

import jax.numpy as jnp


def dedup_frontier(configs):
    fingerprints = [hash(tuple(c)) for c in configs]   # <- hash-dedup
    order = sorted(range(len(configs)),
                   key=lambda i: fingerprints[i])
    return jnp.asarray([configs[i] for i in order])
