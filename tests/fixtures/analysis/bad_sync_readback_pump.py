"""Seeded violation: blocking device readback inside the scheduler
beat (rule ``sync-readback-in-pump``).

``pump`` is the serving loop's beat: it must stage (upload + launch)
and hand the dispatch to the bounded ring, whose deferred finalize
closures do the readback later. An ``np.asarray`` of the engine
result inside pump serializes the beat on the ~100 ms tunnel
round-trip instead of overlapping it with the next bucket's pack."""

import numpy as np


def pump(self, now):
    batch = self._take_bucket(now)
    res = check_device_batch(batch, n_states=64, n_transitions=128)
    verdicts = np.asarray(res)           # finding: sync readback
    self._answer(batch, verdicts)
