"""Seeded violation: listener closed before the pmux withdraw/epoch
bump (rule ``deregister-before-close``).

Clients re-route on the epoch bump. A listener closed first turns
every in-flight ring walk into a connect error against a node the
ring still advertises — the exact ordering PR 12's drain review fixed
in ``daemon._shutdown`` (withdraw FIRST, then stop accepting)."""


def _shutdown(self):
    self._lsock.close()          # finding: close before deregister
    self._pmux_withdraw()
    self._sel.close()
