"""Seeded violation: one verdict dispatch per shrink candidate — the
exact bug ``comdb2_tpu.shrink`` exists to avoid. Each
``check_candidate`` call pays the ~100 ms tunnel round-trip, so a
ddmin round over B candidates is B round-trips; the round's whole
candidate set must ride ``shrink.verdicts.check_candidates`` (ONE
``check_batch`` dispatch per pow2 shape bucket)."""

from comdb2_tpu.shrink.verdicts import check_candidate


def shrink_round(parent, masks, memo):
    verdicts = []
    for m in masks:
        verdicts.append(check_candidate(       # <- per-item-dispatch
            parent, m, memo, F=256))
    return verdicts
