"""Seeded violation: a (7, 100) grid-step block. Blocks need last-two
dims divisible by (8, 128) or equal to the array dims; Mosaic rejects
anything else at compile time."""

from jax.experimental import pallas as pl

SPEC = pl.BlockSpec((7, 100), lambda i: (i, 0))  # <- pallas-block-shape
