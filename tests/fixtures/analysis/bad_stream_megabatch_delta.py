"""Seeded violation: raw (unbucketed) shapes reach the FUSED
streaming-session delta entrypoint — the ``stream_delta_megabatch``
dispatch sink of the ``unbucketed-dispatch-site`` rule. One
unbucketed lane is worse than the solo case: the megabatch's static
table dims are shared by the WHOLE group, so a raw memo count seeds a
fresh program for every same-shape-class batch it ever rides in. The
raw ``memo.n_states`` is laundered through a helper so only the
interprocedural chase can tie the call site to the static shape
argument."""

from comdb2_tpu.stream.engine import stream_delta_megabatch


def _dispatch_group(succs, ip, it, okp, dp, offs, carries, n_states,
                    n_transitions):
    # the sink: the fused session rung's jit entry with static table
    # dims taken from the caller's parameters
    return stream_delta_megabatch(
        succs, ip, it, okp, dp, offs, carries, F=256, Fs=32, P=4,
        n_states=n_states, n_transitions=n_transitions)


def flush_group(lanes, ip, it, okp, dp, offs):
    memo = lanes[0].memo
    succs = tuple(ln.succ_dev for ln in lanes)
    carries = tuple(ln.carry for ln in lanes)
    # BUG: raw memo counts, no pad_sizes/next_pow2 — every append
    # that grew the lead lane's alphabet compiles a fresh fused
    # program for the entire group
    return _dispatch_group(succs, ip, it, okp, dp, offs, carries,
                           memo.n_states, memo.n_transitions)
