"""Seeded violation: raw clock read inside a dispatch-pipeline module.

``time.monotonic()``/``time.time()`` taken directly around a device
dispatch — timing must go through ``comdb2_tpu.obs.trace``
(``monotonic()``, the span API) so queue-wait/device attribution
stays on one clock (rule ``raw-clock-in-pipeline``; the "dispatch"
basename puts this file in the rule's scope, like the production
service/shrink/txn modules)."""

import time
from time import perf_counter


def dispatch_with_raw_clock(engine, batch):
    t0 = time.monotonic()              # finding: raw monotonic
    result = engine.dispatch(batch)
    wall = time.time() - t0            # finding: raw wall clock
    return result, wall, perf_counter()  # finding: from-import form
