"""Seeded violation: parses EDN histories and runs the checker
without offering ``independent.wrap_keyed_history`` — EDN ``[k v]``
values parse as plain tuples, and a bare 2-tuple reads as a cas pair,
so keyed histories silently check the wrong model."""

from comdb2_tpu.checker import analysis
from comdb2_tpu.models.model import MODELS
from comdb2_tpu.ops.native_loader import parse_history_fast


def check_file(path):
    with open(path) as fh:
        history = parse_history_fast(fh.read())   # keyed? nobody asks
    return analysis(MODELS["cas-register"](), history)
