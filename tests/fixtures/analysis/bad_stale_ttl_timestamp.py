"""Seeded violation: blacklist deadline anchored at loop-entry time
(rule ``fresh-deadline-timestamp``).

A hung connect burns its whole timeout before raising, so a TTL
computed from the timestamp taken BEFORE the ring walk is already
(mostly) expired when stored — the dead node is never actually
avoided. Stamp deadlines where they are stored."""

from comdb2_tpu.obs.trace import monotonic


def route(self, shape_class):
    now = monotonic()
    for name in self._ring:
        try:
            return self._connect(name, shape_class)
        except OSError:
            self._avoid[name] = now + self._ttl_s   # finding: stale
    raise OSError("ring exhausted")
