"""Seeded violation: child killed and never waited (rule
``wait-after-kill``).

This container has no init reaper: a ``kill()``/``terminate()`` with
no later ``wait()`` on the SAME process leaves a zombie forever — the
pid table leaks and ``kill -0``-style liveness probes keep answering
"alive" for a corpse (check ``ps -o stat=`` for ``Z``)."""


def retire(self, worker):
    self._deregister(worker.name)
    worker.proc.kill()                   # finding: no wait() follows
    self._workers.remove(worker)
