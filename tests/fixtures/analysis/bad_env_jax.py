"""Seeded violation: JAX env config after jax import.

jax reads env vars at import; the ambient startup hook may have
imported it already, so this assignment silently does nothing and the
suite wedges on the tunneled TPU (ep_poll, 38 minutes)."""

import os

import jax

os.environ["JAX_PLATFORMS"] = "cpu"          # <- jax-env-after-import

assert jax.default_backend() == "cpu"
