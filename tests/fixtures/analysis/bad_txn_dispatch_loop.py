"""Seeded violation: per-graph device dispatch in a host loop — the
txn-checker flavor of ``bad_dispatch_loop.py``. Each ``closure_diag``
call pays the ~100 ms tunnel round-trip; N dependency graphs must be
padded to one bucket and stacked through ``closure_diag_batch`` (or
submitted to the verifier daemon's ``txn`` request kind)."""

import numpy as np

from comdb2_tpu.txn.closure_jax import closure_diag


def classify_all(graphs):
    out = []
    for g in graphs:
        out.append(closure_diag(              # <- per-item-dispatch
            g.padded(np.int32(64))))
    return out
