"""Seeded violation: raw (unbucketed) shapes reach the MXU frontier
engine's batch jit boundary — the new ``check_device_mxu_batch``
dispatch sink of the ``unbucketed-dispatch-site`` rule. The raw
``memo.n_states`` is laundered through a helper so only the
interprocedural chase can tie the call site to the engine entry's
static shape argument; one compiled program per distinct wide-P
history shape, recompiles can OOM LLVM."""

from comdb2_tpu.checker import mxu as MXU


def _dispatch_mxu(succ, sb, n_states, n_transitions):
    # the sink: the MXU batch entry's static table dims come from the
    # caller's parameters
    return MXU.check_device_mxu_batch(
        succ, sb.inv_proc, sb.inv_tr, sb.ok_proc, sb.depth,
        B=8, F=1024, P=16, n_states=n_states,
        n_transitions=n_transitions)


def check_all(batches):
    out = []
    for memo, sb in batches:
        # BUG: raw memo counts, no next_pow2 — every distinct wide-P
        # history shape compiles a fresh program
        out.append(_dispatch_mxu(memo.succ, sb, memo.n_states,
                                 memo.n_transitions))
    return out
