"""Seeded violation: an ``analysis: ignore`` marker whose rule no
longer trips on its line. The suppression audit must flag it —
otherwise dead markers accumulate and silently swallow the NEXT real
finding on their line."""

import numpy as np


def tidy(rows):
    # this line trips nothing: the marker below is pure rot
    out = np.sort(rows)  # analysis: ignore[hash-dedup]
    return out
