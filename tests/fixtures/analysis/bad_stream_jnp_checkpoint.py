"""Seeded violation: a session checkpoint built with DEVICE ops —
the ``host-numpy-checkpoint`` rule. A checkpoint/restore builder is
an eviction/migration artifact: composing it from jnp ops compiles
infra programs (pad/scatter per carry shape) OUTSIDE the declared
``stream-delta`` inventory — one per session shape, re-paid on every
eviction beat — and eagerly round-trips the ~100 ms tunnel, where
``np.asarray`` is a plain readback and the restore upload rides the
next delta dispatch's existing jit transfer."""

import jax.numpy as jnp
import numpy as np


def checkpoint_carry(carry):
    # BUG: jnp.pad/jnp.stack trace + compile a program per carry
    # shape; the snapshot must be np.asarray readbacks only
    states, slots, valid = carry
    wide = jnp.pad(states, (0, 16))
    return {"states": wide, "slots": jnp.stack([slots, slots]),
            "valid": np.asarray(valid)}


def restore_carry(ck):
    # BUG: eager device_put per restore — the next delta dispatch's
    # jit transfer already uploads host numpy for free
    import jax

    return tuple(jax.device_put(np.asarray(x)) for x in ck.values())


def checkpoint_stat(stat):
    # BUG: `import jax.numpy` with NO asname binds the name `jax` —
    # the full jax.numpy.* chain is the same device op and must trip
    # the rule like the aliased form
    import jax.numpy

    return {"stat": jax.numpy.zeros_like(stat)}
