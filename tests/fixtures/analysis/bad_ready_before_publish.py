"""Seeded violation: daemon ready line emitted before the pmux
registration (rule ``publish-before-ready``).

"ready" must mean DISCOVERABLE: the supervisor (and bench harnesses)
route to the daemon the moment the ready line appears, so printing it
before ``publish`` races them against a ring that cannot see the node
yet — and a crash between the two leaves a client-visible server
discovery never lists."""


def serve(pmux, lsock, shard):
    port = lsock.getsockname()[1]
    print("ready", port, flush=True)   # finding: ready before publish
    pmux.publish(f"sut/verifier/{shard}", port)
    return port
