"""Seeded violation: a nemesis completion typed ``ok``. Nemesis
completions must stay ``:info`` (PassThrough client) — an ok/fail
completion would let the nemesis affect the model, and
``history.complete`` rejects the history."""


class FlakyPartitioner:
    def invoke(self, test, op):
        if op["f"] == "start":
            return {**op, "type": "ok", "value": "cut"}
        return {**op, "value": "healed"}
