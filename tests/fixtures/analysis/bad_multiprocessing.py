"""Seeded violation: a multiprocessing pool on the 1-CPU container
(a spawn pool measured 322 s -> 566 s on the 4096x generation)."""

import multiprocessing                        # <- no-multiprocessing


def generate_all(items):
    with multiprocessing.Pool(4) as pool:
        return pool.map(str, items)
