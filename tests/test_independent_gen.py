"""Independent generators, reconnect wrapper, OS setup tests."""

import threading

import pytest

from comdb2_tpu import control
from comdb2_tpu.control.remote import RecordingRemote
from comdb2_tpu.control import reconnect
from comdb2_tpu.harness import generator as G
from comdb2_tpu.harness import independent_gen as IG
from comdb2_tpu.harness import os_setup
from comdb2_tpu.ops.kv import KVTuple

TEST = {"concurrency": 4}


def test_sequential_generator_wraps_and_advances():
    g = IG.sequential_generator(
        [1, 2], lambda k: G.limit(2, {"type": "invoke", "f": "read",
                                      "value": None}))
    vals = []
    while True:
        o = G.op(g, TEST, 0)
        if o is None:
            break
        vals.append(o["value"])
    assert vals == [KVTuple(1, None)] * 2 + [KVTuple(2, None)] * 2
    assert all(isinstance(v, KVTuple) for v in vals)


def test_concurrent_generator_groups():
    # 4 threads, 2 per key -> 2 groups
    seen = {}
    lock = threading.Lock()

    def fgen(k):
        return G.limit(4, {"type": "invoke", "f": "w", "value": k})

    g = IG.concurrent_generator(2, iter(range(10)), fgen)

    def worker(tid):
        with G.with_threads([0, 1, 2, 3]):
            while True:
                o = g.op(TEST, tid)
                if o is None:
                    return
                with lock:
                    seen.setdefault(tid, set()).add(o["value"].key)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # group 0 = threads {0,1}, group 1 = threads {2,3}; keys alternate
    # between groups, and a thread only ever sees its group's keys
    keys01 = seen.get(0, set()) | seen.get(1, set())
    keys23 = seen.get(2, set()) | seen.get(3, set())
    assert keys01 and keys23
    assert keys01.isdisjoint(keys23)
    assert keys01 | keys23 == set(range(10))


def test_concurrent_generator_asserts_divisibility():
    g = IG.concurrent_generator(3, [1], lambda k: G.void)
    with G.with_threads([0, 1, 2, 3]):
        with pytest.raises(AssertionError, match="multiple of 3"):
            g.op(TEST, 0)


def test_concurrent_generator_rejects_nemesis():
    g = IG.concurrent_generator(2, [1], lambda k: G.void)
    with G.with_threads([G.NEMESIS, 0, 1, 2, 3]):
        with pytest.raises(AssertionError, match="integer worker"):
            g.op(TEST, "nemesis")


def test_full_run_with_concurrent_generator(tmp_path):
    """register test lifted over 3 keys with 2 threads per key."""
    from comdb2_tpu.checker import checkers as C
    from comdb2_tpu.checker import independent as I
    from comdb2_tpu.harness import core, fake
    from comdb2_tpu.models import model as M

    states = {}
    lock = threading.Lock()

    class KeyedClient(fake.client_ns.Client):
        def invoke(self, test, op):
            k, v = op["value"]
            with lock:
                cur = states.get(k)
                if op["f"] == "write":
                    states[k] = v
                    return {**op, "type": "ok"}
                if op["f"] == "read":
                    from comdb2_tpu.ops.kv import tuple_
                    return {**op, "type": "ok", "value": tuple_(k, cur)}
            raise ValueError(op["f"])

    import random

    def fgen(k):
        return G.limit(8, lambda t, p: {
            "type": "invoke",
            "f": random.choice(["read", "write"]),
            "value": random.randrange(3)})

    t = fake.noop_test()
    t.update({
        "nodes": [], "concurrency": 6, "name": "indep-gen",
        "store-root": str(tmp_path / "store"),
        "client": KeyedClient(),
        "model": M.register(),
        "generator": G.clients(
            IG.concurrent_generator(2, range(3), fgen)),
        "checker": I.checker(C.Linearizable()),
    })
    result = core.run(t)
    assert result["results"]["valid?"] is True, result["results"]
    assert set(result["results"]["results"]) == {0, 1, 2}


# --- reconnect --------------------------------------------------------------

def test_reconnect_reopens_after_failure():
    opens = []

    class FragileConn:
        def __init__(self, gen_):
            self.gen = gen_
            self.alive = True

    def open_fn():
        opens.append(1)
        return FragileConn(len(opens))

    closed = []
    w = reconnect.wrapper(open_fn, lambda c: closed.append(c.gen))
    assert w.with_conn(lambda c: c.gen) == 1
    assert w.with_conn(lambda c: c.gen) == 1      # reused

    def boom(c):
        raise IOError("dropped")

    with pytest.raises(IOError):
        w.with_conn(boom)
    assert closed == [1]
    assert w.with_conn(lambda c: c.gen) == 2      # reopened

    # with_retry succeeds across a transient failure
    calls = []

    def flaky(c):
        calls.append(c.gen)
        if len(calls) == 1:
            raise IOError("once")
        return c.gen

    assert w.with_retry(flaky, retries=3, delay=0) == 3


# --- os ---------------------------------------------------------------------

def test_debian_os_setup_commands():
    rec = RecordingRemote()
    test = {"nodes": ["n1"], "remote": rec}
    os_ = os_setup.DebianOS(packages=["ntpdate", "iptables"],
                            node_ips={"n1": "10.0.0.1",
                                      "n2": "10.0.0.2"})
    control.on_nodes(test, os_.setup)
    cmds = [c for _, c in rec.commands]
    assert any("/etc/hostname" in c for c in cmds)
    assert any("10.0.0.2 n2" in c for c in cmds)
    assert any("apt-get install -y ntpdate iptables" in c for c in cmds)
