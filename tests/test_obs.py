"""The observability plane: span tracing (nesting, rid correlation,
Perfetto export, disabled-mode no-op), histogram quantile math vs
exact samples, the Prometheus/JSON scrape shapes, per-request stage
attribution tiling the measured wall, the timeline SVG, filetest
--trace, and the daemon wire round-trip (scrape + shutdown trace
artifact)."""

import json
import os
import random
import subprocess
import sys
import time

import pytest

from comdb2_tpu.obs import trace
from comdb2_tpu.obs.metrics import (DEFAULT_MS_BUCKETS, Histogram,
                                    Registry)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tracing():
    """Enabled tracing scoped to one test — the flag and span buffer
    are process-global."""
    trace.clear()
    trace.enable()
    try:
        yield trace
    finally:
        trace.disable()
        trace.clear()


# --- histogram quantile math -------------------------------------------------

def _bracket(edges, v):
    """(lo, hi) bucket edges containing v."""
    lo = 0.0
    for e in edges:
        if v <= e:
            return lo, e
        lo = e
    return lo, lo


def test_histogram_quantiles_vs_exact_samples():
    """The golden contract: every derived quantile lands inside the
    bucket bracketing the EXACT sample quantile (error <= bucket
    width, as documented)."""
    rng = random.Random(7)
    h = Histogram()
    samples = [rng.uniform(0, 3000) for _ in range(4000)]
    for v in samples:
        h.observe(v)
    samples.sort()
    for q in (0.5, 0.95, 0.99):
        exact = samples[int(q * (len(samples) - 1))]
        lo, hi = _bracket(DEFAULT_MS_BUCKETS, exact)
        est = h.quantile(q)
        assert lo * 0.99 <= est <= hi * 1.01, (q, exact, est, lo, hi)
    assert h.count == 4000
    assert abs(h.sum - sum(samples)) < 1e-6 * sum(samples)


def test_histogram_edges_and_overflow():
    h = Histogram(buckets=(10, 100))
    for v in (5, 50, 500, 5000):
        h.observe(v)
    snap = h.snapshot()
    assert snap["buckets"] == [[10, 1], [100, 2], ["+Inf", 4]]
    # overflow clamps to the last finite edge — an honest "at least"
    assert h.quantile(0.99) == 100


# --- registry render shapes --------------------------------------------------

def test_registry_prometheus_and_json_shapes():
    r = Registry()
    r.counter("svc_reqs_total", help="requests").inc(3)
    r.gauge("svc_depth").set(7)
    h = r.histogram("svc_lat_ms", buckets=(1, 10))
    h.observe(0.5)
    h.observe(5)
    r.gauge("svc_occ", bucket="n16-s8").set(0.5)

    snap = r.snapshot()
    assert snap["svc_reqs_total"]["type"] == "counter"
    assert snap["svc_reqs_total"]["series"][0]["value"] == 3
    s = snap["svc_lat_ms"]["series"][0]
    assert s["count"] == 2 and s["buckets"][-1] == ["+Inf", 2]
    assert snap["svc_occ"]["series"][0]["labels"] == {
        "bucket": "n16-s8"}
    json.dumps(snap)                      # wire-safe

    text = r.render_prometheus()
    assert "# TYPE svc_lat_ms histogram" in text
    assert 'svc_lat_ms_bucket{le="1"} 1' in text
    assert 'svc_lat_ms_bucket{le="+Inf"} 2' in text
    assert "svc_lat_ms_count 2" in text
    assert 'svc_occ{bucket="n16-s8"} 0.5' in text
    # cumulative bucket counts must be monotone
    cums = [int(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
            if ln.startswith("svc_lat_ms_bucket")]
    assert cums == sorted(cums)

    with pytest.raises(ValueError):
        r.counter("svc_depth")            # type mismatch is an error


# --- span tracing ------------------------------------------------------------

def test_spans_nest_and_correlate(tracing):
    with trace.request(41):
        with trace.span("outer", k=1):
            with trace.span("inner"):
                time.sleep(0.001)
    trace.record("retro", 1.0, 2.0, rid=9, bytes_h2d=128)
    spans = {s.name: s for s in trace.spans()}
    assert set(spans) == {"outer", "inner", "retro"}
    inner, outer = spans["inner"], spans["outer"]
    assert inner.parent is outer
    assert inner.rid == outer.rid == 41
    # nesting: the child interval is contained in the parent's
    assert outer.t0 <= inner.t0 and inner.t1 <= outer.t1
    assert spans["retro"].rid == 9

    doc = trace.export_chrome()
    ev = {e["name"]: e for e in doc["traceEvents"]}
    assert ev["inner"]["args"] == {"rid": 41, "parent": "outer"}
    assert ev["retro"]["args"]["bytes_h2d"] == 128
    assert ev["retro"]["dur"] == pytest.approx(1e6)
    json.dumps(doc)                       # Perfetto-loadable JSON


def test_disabled_mode_is_a_noop():
    trace.disable()
    trace.clear()
    # one shared no-op context manager, nothing recorded
    assert trace.span("a") is trace.span("b", k=1)
    with trace.span("a") as s:
        assert s.set(x=1) is s
    trace.record("r", 0.0, 1.0)
    assert trace.spans() == []
    assert not trace.enabled()


def test_span_buffer_is_bounded(tracing):
    trace.enable(max_spans=8)
    try:
        for i in range(20):
            with trace.span(f"s{i}"):
                pass
        assert len(trace.spans()) == 8
        assert trace.dropped_spans() == 12
        assert trace.export_chrome()["otherData"][
            "dropped_spans"] == 12
    finally:
        trace.enable()                    # restore default cap


# --- the service surfaces ----------------------------------------------------

def _core(**kw):
    from comdb2_tpu.service import VerifierCore

    kw.setdefault("F", 64)
    kw.setdefault("batch_cap", 8)
    return VerifierCore(**kw)


def _submit(core, h, **fields):
    from comdb2_tpu.ops.history import history_to_edn

    return core.submit({"op": "check",
                        "history": history_to_edn(list(h)),
                        **fields}, time.monotonic())


def test_metrics_kind_scrape_round_trip():
    """Golden shape of the kind:"metrics" reply — and it answers even
    at a full queue (served ahead of backpressure)."""
    from comdb2_tpu.ops.synth import register_history

    core = _core(max_queue=1)
    h = register_history(random.Random(2), 3, 24, p_info=0.0)
    _submit(core, h)
    core.tick()
    _, reply = core.submit({"op": "check", "kind": "metrics",
                            "id": 5}, time.monotonic())
    assert reply["ok"] and reply["kind"] == "metrics"
    assert reply["id"] == 5
    snap = reply["metrics"]
    for name in ("service_queue_wait_ms", "service_host_pack_ms",
                 "service_device_ms", "service_finalize_ms",
                 "service_latency_ms"):
        series = snap[name]["series"][0]
        assert {"count", "sum", "p50", "p95", "p99",
                "buckets"} <= set(series)
    assert snap["service_queue_wait_ms"]["series"][0]["count"] >= 1
    assert snap["service_dispatches_total"]["series"][0]["value"] >= 1
    assert snap["compile_xla_lowerings_total"]["series"][0][
        "value"] >= 0
    assert "service_queue_wait_ms_bucket{" in reply["prometheus"]
    json.dumps(reply)                     # one wire-safe frame
    # scrape while the queue is at cap: still answered, not overload
    assert _submit(core, h)[0] is not None          # fills the queue
    _, r2 = core.submit({"op": "check", "kind": "metrics"},
                        time.monotonic())
    assert r2["ok"] and r2["kind"] == "metrics"
    core.tick()


def test_reply_stages_tile_latency():
    """The attribution contract bench_service asserts at scale: per
    reply, sum(stages) ~= latency_ms."""
    from comdb2_tpu.ops.synth import register_history

    core = _core()
    for seed in (3, 4):
        _submit(core, register_history(random.Random(seed), 3, 24,
                                       p_info=0.0))
    done = core.tick()
    assert done
    for _, reply in done:
        stages = reply["stages"]
        assert set(stages) == {"queue_wait_ms", "host_pack_ms",
                               "device_ms", "finalize_ms"}
        total = sum(stages.values())
        assert abs(total - reply["latency_ms"]) <= \
            max(0.1 * reply["latency_ms"], 5.0), reply
    st = core.status()
    assert st["stage_ms"]["queue_wait"]["n"] >= 2
    assert st["transfer_bytes"]["h2d"] > 0


def test_expired_reply_stages_tile_latency():
    """The expiry path keeps the attribution contract: a deadline-
    expired request's reply carries ALL four stages (its whole wait
    is queue wait; the rest observe as 0), sum(stages) tiles its
    latency_ms, and the stage histograms share the latency
    histogram's count — the 153-vs-258 count mismatch this round
    fixed."""
    from comdb2_tpu.ops.synth import register_history
    from comdb2_tpu.service.core import STAGES

    core = _core()
    h = register_history(random.Random(9), 3, 24, p_info=0.0)
    _submit(core, h, deadline_ms=0)       # expired on arrival
    _submit(core, h)
    time.sleep(0.002)
    done = core.tick()
    expired = next(r for _, r in done if r["valid"] == "unknown")
    assert expired["cause"] == "deadline"
    stages = expired["stages"]
    assert set(stages) == set(STAGES)
    assert stages["queue_wait_ms"] > 0
    assert stages["host_pack_ms"] == stages["device_ms"] == \
        stages["finalize_ms"] == 0.0
    total = sum(stages.values())
    assert abs(total - expired["latency_ms"]) <= \
        max(0.1 * expired["latency_ms"], 5.0), expired
    # histogram counts tile: every stage series counts EVERY
    # completed request, expiries included
    snap = core.metrics_reply()["metrics"]
    n_lat = snap["service_latency_ms"]["series"][0]["count"]
    assert n_lat == len(done) == 2
    for s in STAGES:
        name = "service_" + s.replace("_ms", "") + "_ms"
        assert snap[name]["series"][0]["count"] == n_lat, name


def test_expired_shrink_partial_stages_tile_latency():
    """A shrink job cut by its deadline BETWEEN rounds charges the
    final re-queue wait to queue_wait, so the partial reply's stages
    still tile its latency (review regression — real clocks
    throughout, the stage math and the expiry share one timebase)."""
    import random as _random

    from comdb2_tpu.ops.history import history_to_edn
    from comdb2_tpu.ops.synth import inject_anomaly, register_history
    from comdb2_tpu.service.core import STAGES

    core = _core()
    base = register_history(_random.Random(23), 3, 200,
                            fs=("write",), p_info=0.0)
    h, _ = inject_anomaly(base, "stale-read")
    _, reply = core.submit(
        {"op": "check", "kind": "shrink", "id": 3,
         "history": history_to_edn(h), "deadline_ms": 50},
        time.monotonic())
    assert reply is None
    deadline = time.monotonic() + 120
    done = []
    while not done and time.monotonic() < deadline:
        done = core.pump(time.monotonic())
    (_, r), = done
    if not r.get("partial"):
        pytest.skip("minimization finished inside the deadline — "
                    "nothing expired between rounds")
    assert r["cause"] == "deadline"
    stages = r["stages"]
    assert set(stages) == set(STAGES)
    total = sum(stages.values())
    assert abs(total - r["latency_ms"]) <= \
        max(0.1 * r["latency_ms"], 5.0), r


def test_priming_stays_out_of_the_histograms():
    core = _core()
    core.prime(specs=((24, 2),), seed=41)
    assert core.metrics_reply()["metrics"][
        "service_latency_ms"]["series"][0]["count"] == 0
    records, _ = core.timeline_records()
    assert records == []


def test_timeline_svg_renders_stages_and_events():
    from comdb2_tpu.report.service_svg import render_service_timeline

    records = [{"t": 0.2 + i * 0.1, "lat_ms": 5.0 + i, "kind": "check",
                "valid": True,
                "stages": {"queue_wait_ms": 2.0, "host_pack_ms": 1.0,
                           "device_ms": 2.0, "finalize_ms": 0.1}}
               for i in range(20)]
    events = [{"t": 1.0, "event": "overload"},
              {"t": 1.5, "event": "deadline"}]
    svg = render_service_timeline(records, events)
    assert svg.startswith("<svg")
    assert "queue_wait" in svg and "device" in svg
    assert svg.count("stroke-dasharray") >= 2      # event markers
    # degenerate inputs must not crash the artifact pass
    assert render_service_timeline([], []).startswith("<svg")


def test_filetest_trace_artifact(tmp_path):
    """filetest --trace writes a loadable Perfetto export with the
    parse/check spans (host backend: no device needed)."""
    from comdb2_tpu.filetest import main as filetest_main
    from comdb2_tpu.ops.history import history_to_edn
    from comdb2_tpu.ops.synth import register_history

    h = register_history(random.Random(6), 3, 16, p_info=0.0)
    edn = tmp_path / "hist.edn"
    edn.write_text(history_to_edn(list(h)))
    out = tmp_path / "trace.json"
    rc = filetest_main([str(edn), "--backend", "host",
                        "--trace", str(out)])
    assert rc == 0
    assert not trace.enabled()            # flag must not leak onward
    doc = json.loads(out.read_text())
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"filetest.parse", "linear.analysis",
            "linear.pack"} <= names, names


# --- the wire ----------------------------------------------------------------

def test_daemon_metrics_and_trace_artifacts(tmp_path):
    """End to end: daemon --trace --store, one check, scrape over the
    wire, shutdown writes trace.json + timeline.svg, store web index
    links them."""
    from comdb2_tpu.ops.synth import register_history
    from comdb2_tpu.service.client import ServiceClient

    store = tmp_path / "store"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [sys.executable, "-m", "comdb2_tpu.service", "--port", "0",
         "--backend", "cpu", "--no-prime", "--frontier", "64",
         "--coalesce-ms", "2", "--trace", "--store", str(store)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=ROOT, env=env)
    try:
        ready = json.loads(proc.stdout.readline())
        assert ready.get("ready") and ready.get("trace"), ready
        c = ServiceClient("127.0.0.1", ready["port"],
                          timeout_s=300.0)
        h = register_history(random.Random(5), 3, 24, p_info=0.0)
        r = c.check(h)
        assert r["ok"] and r.get("stages"), r
        m = c.metrics()
        assert m["ok"] and m["kind"] == "metrics"
        assert m["metrics"]["service_dispatches_total"]["series"][0][
            "value"] >= 1
        st = c.status()["status"]
        assert st["tracing"] is True
        assert c.shutdown()
    finally:
        try:
            rc = proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)
            raise
    assert rc == 0
    doc = json.loads((store / "service" / "trace.json").read_text())
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"admission", "stage", "device", "finalize",
            "request"} <= names, names
    dev = [e for e in doc["traceEvents"] if e["name"] == "device"]
    assert any(e["args"].get("bytes_h2d", 0) > 0 for e in dev)
    assert (store / "service" / "timeline.svg").exists()
    from comdb2_tpu.harness.web import _index_html

    idx = _index_html(str(store))
    assert "trace.json" in idx and "timeline.svg" in idx
