"""Host JIT-linearization engine vs. hand-built cases and the brute
oracle."""

import random

import pytest

from comdb2_tpu.checker import linear_host
from comdb2_tpu.checker.brute import brute_valid
from comdb2_tpu.models.memo import memo as make_memo
from comdb2_tpu.models import model as M
from comdb2_tpu.ops import op as O
from comdb2_tpu.ops.packed import pack_history

import histgen


def run(model, history):
    packed = pack_history(history)
    mm = make_memo(model, packed)
    return linear_host.check(mm, packed)


def test_sequential_register_valid():
    h = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
         O.invoke(0, "read", None), O.ok(0, "read", 1)]
    assert run(M.register(), h).valid


def test_stale_read_invalid():
    h = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
         O.invoke(0, "read", None), O.ok(0, "read", 2)]
    r = run(M.register(), h)
    assert not r.valid
    assert r.op_index == 3


def test_concurrent_read_may_see_either():
    # read overlaps the write: both 1 (new) and None (old) are fine
    for seen in (1, None):
        h = [O.invoke(0, "write", 1),
             O.invoke(1, "read", None),
             O.ok(1, "read", seen),
             O.ok(0, "write", 1)]
        assert run(M.register(), h).valid
    # a non-overlapping later read must see the write (note: a *nil*-valued
    # completed read means "result unknown" and matches any state, per the
    # reference Register model, knossos/model.clj:48-65)
    h = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
         O.invoke(1, "read", None), O.ok(1, "read", 2)]
    assert not run(M.register(), h).valid


def test_cas_semantics():
    h = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
         O.invoke(0, "cas", (1, 2)), O.ok(0, "cas", (1, 2)),
         O.invoke(0, "read", None), O.ok(0, "read", 2)]
    assert run(M.cas_register(), h).valid
    h[3] = O.ok(0, "cas", (3, 2))
    h[2] = O.invoke(0, "cas", (3, 2))
    assert not run(M.cas_register(), h).valid


def test_failed_op_never_happened():
    # failed write must NOT be visible
    h = [O.invoke(0, "write", 1), O.fail(0, "write", 1),
         O.invoke(0, "read", None), O.ok(0, "read", 1)]
    assert not run(M.register(), h).valid
    h = [O.invoke(0, "write", 1), O.fail(0, "write", 1),
         O.invoke(0, "read", None), O.ok(0, "read", None)]
    assert run(M.register(), h).valid


def test_info_op_may_or_may_not_happen():
    # crashed write: both outcomes legal (history.clj:127-145 semantics)
    for seen in (1, None):
        h = [O.invoke(0, "write", 1), O.info(0, "write", 1),
             O.invoke(1, "read", None), O.ok(1, "read", seen)]
        assert run(M.register(), h).valid, f"seen={seen}"


def test_info_op_pins_later_state():
    # committed write of 9; crashed write of 1; a read seeing 1 pins the
    # crashed write as linearized, so a later read must not see 9 again
    h = [O.invoke(1, "write", 9), O.ok(1, "write", 9),
         O.invoke(0, "write", 1), O.info(0, "write", 1),
         O.invoke(1, "read", None), O.ok(1, "read", 1),
         O.invoke(1, "read", None), O.ok(1, "read", 9)]
    assert not run(M.register(), h).valid


def test_mutex():
    h = [O.invoke(0, "acquire", None), O.ok(0, "acquire", None),
         O.invoke(1, "acquire", None),
         O.invoke(0, "release", None), O.ok(0, "release", None),
         O.ok(1, "acquire", None)]
    assert run(M.mutex(), h).valid
    # two non-overlapping acquires with no release: invalid
    h = [O.invoke(0, "acquire", None), O.ok(0, "acquire", None),
         O.invoke(1, "acquire", None), O.ok(1, "acquire", None)]
    assert not run(M.mutex(), h).valid


def test_fifo_queue():
    h = [O.invoke(0, "enqueue", 1), O.ok(0, "enqueue", 1),
         O.invoke(0, "enqueue", 2), O.ok(0, "enqueue", 2),
         O.invoke(1, "dequeue", None), O.ok(1, "dequeue", 1)]
    assert run(M.fifo_queue(), h).valid
    h[-1] = O.ok(1, "dequeue", 2)
    assert not run(M.fifo_queue(), h).valid


def test_empty_history_valid():
    assert run(M.register(), []).valid


@pytest.mark.parametrize("seed", range(60))
def test_random_valid_histories(seed):
    rng = random.Random(seed)
    h = histgen.register_history(rng, n_procs=rng.randint(2, 4),
                                 n_events=rng.randint(4, 14))
    model = M.cas_register()
    got = run(model, h)
    want = brute_valid(model, h)
    assert want, "generator must produce linearizable histories"
    assert got.valid == want


@pytest.mark.parametrize("seed", range(120))
def test_random_mutated_histories_match_oracle(seed):
    rng = random.Random(10_000 + seed)
    h = histgen.register_history(rng, n_procs=rng.randint(2, 4),
                                 n_events=rng.randint(4, 12))
    h = histgen.mutate(rng, h)
    model = M.cas_register()
    got = run(model, h)
    want = brute_valid(model, h)
    assert got.valid == want
