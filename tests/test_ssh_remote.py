"""SSHRemote executed end to end (round-4 VERDICT Weak #8 / Next #7).

No OpenSSH exists in this container, so these tests install ``ssh`` /
``scp`` SHIM executables on PATH that honor the argv surface SSHRemote
builds (-o/-p/-i options, ``user@host`` targets, ``host:path`` copy
syntax), run the command locally, and can simulate dropped connections
(exit 255 — ssh's own "connection failed" code) via a countdown file.
The transport code under test is the REAL one: argv assembly, retry
policy, 255-vs-command-exit discrimination, timeout handling, scp
destination syntax (``control.clj:233-256``, ``reconnect.clj:92-129``).
"""

import os
import socket
import stat
import sys
from types import SimpleNamespace

import pytest

from comdb2_tpu.control.remote import SSHRemote

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BINARY = os.path.join(ROOT, "native", "build", "sut_node")

# /bin/sh, not python: the container's interpreter-startup hook
# pre-imports jax for python processes launched from the repo cwd —
# seconds of startup per shim call would distort the timeout test and
# slow every provisioning step
SSH_SHIM = r'''#!/bin/sh
port=""
while [ $# -gt 0 ]; do
  case "$1" in
    -o|-i) shift 2 ;;
    -p) port="$2"; shift 2 ;;
    *) break ;;
  esac
done
host="$1"; shift
cmd="$*"
[ -n "$SSH_SHIM_LOG" ] && \
  printf 'ssh %s port=%s :: %s\n' "$host" "$port" "$cmd" >> "$SSH_SHIM_LOG"
if [ -n "$SSH_SHIM_FAIL_FILE" ] && [ -f "$SSH_SHIM_FAIL_FILE" ]; then
  n=$(cat "$SSH_SHIM_FAIL_FILE" 2>/dev/null || echo 0)
  case "$n" in ''|*[!0-9]*) n=0 ;; esac
  if [ "$n" -gt 0 ]; then
    echo $((n-1)) > "$SSH_SHIM_FAIL_FILE"
    echo "ssh: connect to host $host: Connection refused" >&2
    exit 255
  fi
fi
exec /bin/sh -c "$cmd"
'''

SCP_SHIM = r'''#!/bin/sh
while [ $# -gt 0 ]; do
  case "$1" in
    -o|-i|-P) shift 2 ;;
    *) break ;;
  esac
done
src="$1"; dst="$2"
[ -n "$SSH_SHIM_LOG" ] && printf 'scp %s %s\n' "$src" "$dst" >> "$SSH_SHIM_LOG"
if [ -n "$SSH_SHIM_FAIL_FILE" ] && [ -f "$SSH_SHIM_FAIL_FILE" ]; then
  n=$(cat "$SSH_SHIM_FAIL_FILE" 2>/dev/null || echo 0)
  case "$n" in ''|*[!0-9]*) n=0 ;; esac
  if [ "$n" -gt 0 ]; then
    echo $((n-1)) > "$SSH_SHIM_FAIL_FILE"
    echo "scp: Connection refused" >&2
    exit 255
  fi
fi
strip() {
  case "$1" in
    *:*) f="${1%%:*}"
         if [ -e "$f" ]; then printf '%s' "$1"
         else p="${1#*:}"
              # the remote shell would unquote the path; do the same
              case "$p" in "'"*"'") p="${p#\'}"; p="${p%\'}" ;; esac
              printf '%s' "$p"; fi ;;
    *) printf '%s' "$1" ;;
  esac
}
exec cp "$(strip "$src")" "$(strip "$dst")"
'''


@pytest.fixture
def shim(tmp_path, monkeypatch):
    d = tmp_path / "shimbin"
    d.mkdir()
    for name, body in (("ssh", SSH_SHIM), ("scp", SCP_SHIM)):
        p = d / name
        p.write_text(body)
        p.chmod(p.stat().st_mode | stat.S_IEXEC)
    log = tmp_path / "shim.log"
    fail = tmp_path / "shim.failures"
    monkeypatch.setenv("PATH", f"{d}:{os.environ['PATH']}")
    monkeypatch.setenv("SSH_SHIM_LOG", str(log))
    monkeypatch.setenv("SSH_SHIM_FAIL_FILE", str(fail))

    def log_lines():
        return log.read_text().splitlines() if log.exists() else []

    return SimpleNamespace(log_lines=log_lines, fail=fail)


def test_execute_roundtrip_and_argv_surface(shim):
    r = SSHRemote(ssh_opts={"username": "admin", "port": 2222})
    res = r.execute("n1", "echo hello && echo oops >&2; exit 3")
    assert res.rc == 3
    assert res.out == "hello\n"
    assert "oops" in res.err
    (line,) = shim.log_lines()
    assert line.startswith("ssh admin@n1 port=2222 :: ")


def test_retry_on_dropped_connection(shim):
    """Two refused connections, then success: the 255 retry loop (the
    reconnect role) must re-send and succeed on the third attempt."""
    shim.fail.write_text("2")
    r = SSHRemote(retries=3, retry_delay=0.01)
    res = r.execute("n2", "echo back")
    assert res.ok and res.out == "back\n"
    assert len([l for l in shim.log_lines() if "ssh" in l]) == 3


def test_retries_exhausted_reports_unreachable(shim):
    shim.fail.write_text("99")
    r = SSHRemote(retries=2, retry_delay=0.01)
    res = r.execute("n3", "echo never")
    assert res.rc == 255
    assert "refused" in res.err
    assert len(shim.log_lines()) == 2


def test_command_failure_is_not_retried(shim):
    """A non-255 exit is the REMOTE COMMAND's status — retrying could
    re-apply a non-idempotent op."""
    r = SSHRemote(retries=3, retry_delay=0.01)
    res = r.execute("n1", "exit 17")
    assert res.rc == 17
    assert len(shim.log_lines()) == 1


def test_timeout_never_resends(shim):
    r = SSHRemote(retries=3, retry_delay=0.01)
    res = r.execute("n1", "sleep 5", timeout=0.4)
    assert res.rc == -1
    assert "timeout" in res.err
    assert len(shim.log_lines()) == 1


def test_upload_download(shim, tmp_path):
    src = tmp_path / "payload"
    src.write_text("cargo\n")
    dst = tmp_path / "remote-side"
    back = tmp_path / "returned"
    r = SSHRemote(ssh_opts={"username": "root"})
    r.upload("n4", str(src), str(dst))
    assert dst.read_text() == "cargo\n"
    r.download("n4", str(dst), str(back))
    assert back.read_text() == "cargo\n"
    assert any(l.startswith("scp") for l in shim.log_lines())


def test_upload_remote_path_with_spaces(shim, tmp_path):
    """scp's remote side word-splits through the remote shell; _dest
    must quote the path (the shim unquotes like a remote shell)."""
    (tmp_path / "my dir").mkdir()
    src = tmp_path / "payload2"
    src.write_text("x\n")
    dst = tmp_path / "my dir" / "bin file"
    r = SSHRemote()
    r.upload("n5", str(src), str(dst))
    assert dst.read_text() == "x\n"


# --- the flagship loop over SSHRemote with a mid-run reconnect -------------

class _SshChaosNemesis:
    """Nemesis that exercises the CONTROL plane mid-run: drops the next
    ssh connection (countdown file), then issues a control command
    through the SAME SSHRemote the provisioner uses — the first attempt
    gets 255, the retry reconnects and succeeds."""

    def __init__(self, remote, fail_file):
        self.remote = remote
        self.fail_file = fail_file
        self.reconnects = 0

    def setup(self, test, node):
        return self

    def invoke(self, test, op):
        if op["f"] == "drop-ssh":
            self.fail_file.write_text("1")
            res = self.remote.execute("m1", "echo control-plane-alive")
            assert res.ok and "control-plane-alive" in res.out, res
            self.reconnects += 1
            return {**op, "value": "reconnected"}
        return op

    def teardown(self, test):
        pass


@pytest.mark.skipif(not os.path.exists(BINARY),
                    reason="sut_node not built")
def test_provisioned_cluster_over_ssh_remote_with_reconnect(shim,
                                                            tmp_path):
    """The provision -> cluster -> workload -> verdict loop with EVERY
    control-plane action (install, config, daemon start, readiness,
    teardown) riding SSHRemote, plus a mid-run ssh connection drop that
    the transport must absorb via its retry/reconnect policy."""
    from comdb2_tpu.checker.workloads import bank_checker
    from comdb2_tpu.harness import core, fake
    from comdb2_tpu.harness import generator as G
    from comdb2_tpu.harness.provision import SutNodeDB, local_layout
    from comdb2_tpu.workloads import comdb2 as W
    from comdb2_tpu.workloads.tcp import BankTcpClient

    def _free_ports(n):
        socks, ports = [], []
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
        for s in socks:
            s.close()
        return ports

    nodes = ["m1", "m2", "m3"]
    ports = _free_ports(3)
    base = str(tmp_path / "sut")
    remote = SSHRemote(ssh_opts={"username": "root"}, retries=3,
                       retry_delay=0.05)
    db = SutNodeDB(remote, BINARY, local_layout(nodes, ports),
                   base_dir=base, timeout_ms=500, elect_ms=500,
                   lease_ms=300)
    nemesis = _SshChaosNemesis(remote, shim.fail)
    n = 4
    t = fake.noop_test()
    t.update({
        "nodes": nodes, "concurrency": 4, "name": "ssh-remote-bank",
        "store-root": str(tmp_path / "store"),
        "db": db,
        "client": BankTcpClient(ports, n=n, timeout_s=0.6),
        "nemesis": nemesis,
        "model": {"n": n, "total": n * 10},
        "_bank_n": n,
        "generator": G.nemesis(
            G.seq([G.sleep(1.0), {"type": "info", "f": "drop-ssh"},
                   G.sleep(1.0), {"type": "info", "f": "drop-ssh"}]),
            G.time_limit(3.0, G.stagger(
                0.01, G.mix([W.bank_read, W.bank_diff_transfer])))),
        "checker": bank_checker,
    })
    result = core.run(t)
    try:
        assert result["results"]["valid?"] is True, result["results"]
        # the control plane really rode ssh: install/start/teardown
        lines = shim.log_lines()
        assert any("scp" in l for l in lines), "binary upload not via scp"
        joined = "\n".join(lines)
        assert "root@127.0.0.1" in joined      # local_layout host
        for node in nodes:                     # every node provisioned
            assert f"sut/{node}/pid" in joined
        assert nemesis.reconnects == 2
        # the drop really happened: 255 lines exist (same command twice)
        assert joined.count("echo control-plane-alive") >= 4
    finally:
        for node in nodes:
            db.teardown(t, node)
