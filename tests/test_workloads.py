"""Workload tests: the comdb2 suite against the in-memory serializable
backend — and negative controls with chaos/bugs injected."""

import pytest

from comdb2_tpu.harness import core
from comdb2_tpu.workloads import comdb2 as W
from comdb2_tpu.workloads.sqlish import Indeterminate, MemDB, Rollback


def _small(test, tmp_path):
    test["store-root"] = str(tmp_path / "store")
    test["nodes"] = []
    return test


def test_memdb_serializable_txns():
    db = MemDB()
    c = db.connect()
    c.insert("t", {"id": 1, "v": 10})
    assert c.select("t", lambda r: r["id"] == 1)[0]["v"] == 10
    assert c.update("t", {"v": 11}, lambda r: r["id"] == 1) == 1
    assert c.update("t", {"v": 9}, lambda r: r["id"] == 99) == 0
    assert c.delete("t") == 1
    assert c.select("t") == []


def test_memdb_rollback_discards_buffered_writes():
    db = MemDB()
    c = db.connect()
    with pytest.raises(RuntimeError):
        with c.transaction() as t:
            t.insert("t", {"id": 1})
            raise RuntimeError("abort")
    assert c.select("t") == []


def test_memdb_chaos_outcomes():
    db = MemDB(chaos_fail=1.0)
    c = db.connect()
    with pytest.raises(Rollback):
        c.insert("t", {"id": 1})
    db2 = MemDB(chaos_unknown=1.0, seed=4)
    c2 = db2.connect()
    applied = 0
    for i in range(20):
        with pytest.raises(Indeterminate):
            c2.insert("t", {"id": i})
    applied = len(c2.db.tables.get("t", []))
    assert 0 < applied < 20      # some committed, some didn't


def test_register_workload_valid(tmp_path):
    t = _small(W.register_tester(time_limit=1.5), tmp_path)
    t["concurrency"] = 6
    result = core.run(t)
    assert result["results"]["valid?"] is True, result["results"]
    lin = result["results"]["linearizable"]
    assert lin["valid?"] is True
    assert len(result["history"]) > 20


def test_register_workload_with_chaos_still_valid(tmp_path):
    db = MemDB(chaos_fail=0.1, chaos_unknown=0.05, seed=1)
    t = _small(W.register_tester(connect=db.connect, time_limit=1.5),
               tmp_path)
    t["concurrency"] = 6
    result = core.run(t)
    # fails and indeterminates are normal; the history must stay
    # linearizable because MemDB itself is correct
    assert result["results"]["valid?"] is True, result["results"]
    assert any(op.type == "info" for op in result["history"])


def test_bank_workload(tmp_path):
    t = _small(W.bank_test(time_limit=1.5, n=4), tmp_path)
    t["concurrency"] = 6
    result = core.run(t)
    assert result["results"]["valid?"] is True, result["results"]
    reads = [op for op in result["history"]
             if op.type == "ok" and op.f == "read" and op.value]
    assert reads
    assert all(sum(op.value) == 40 for op in reads)


def test_sets_workload(tmp_path):
    t = _small(W.sets_test(adds=40), tmp_path)
    t["concurrency"] = 5
    result = core.run(t)
    assert result["results"]["valid?"] is True, result["results"]
    assert result["results"]["ok-frac"] == 1.0


def test_sets_workload_lossy_backend_detected(tmp_path):
    from comdb2_tpu.workloads.sqlish import MemConn

    db = MemDB()
    db.counter = 0

    class LossyConn(MemConn):
        """Acks every 5th write txn but silently discards its buffered
        writes at commit — data loss the checker must catch."""

        def transaction(self):
            ctx = super().transaction()
            conn_db = self.db

            class MaybeDropCtx:
                def __enter__(s):
                    s.t = ctx.__enter__()
                    return s.t

                def __exit__(s, *a):
                    if a[0] is None and s.t.writes:
                        conn_db.counter += 1
                        if conn_db.counter % 5 == 0:
                            s.t.writes.clear()    # lost update
                    return ctx.__exit__(*a)
            return MaybeDropCtx()

    t = _small(W.sets_test(connect=lambda: LossyConn(db), adds=40),
               tmp_path)
    t["concurrency"] = 5
    result = core.run(t)
    assert result["results"]["valid?"] is False
    assert result["results"]["lost"] != "#{}"


def test_dirty_reads_workload(tmp_path):
    t = _small(W.dirty_reads_tester(time_limit=1.0, n=3), tmp_path)
    result = core.run(t)
    assert result["results"]["valid?"] is True, result["results"]


def test_g2_workload(tmp_path):
    t = _small(W.g2_test(ops=60), tmp_path)
    t["concurrency"] = 6
    result = core.run(t)
    # serializable backend: at most one insert per key ever commits
    assert result["results"]["valid?"] is True, result["results"]
    assert result["results"]["key-count"] >= 1


def test_g2_broken_backend_detected(tmp_path):
    """A backend whose predicate reads miss concurrent inserts lets both
    G2 inserts commit — the checker must flag it."""
    from comdb2_tpu.harness import client as client_ns
    from comdb2_tpu.checker.independent import KVTuple

    class BrokenG2Client(client_ns.Client):
        def __init__(self):
            self.committed = {}

        def setup(self, test, node):
            return self

        def invoke(self, test, op):
            k = op["value"][0]
            # no predicate check at all: every insert succeeds
            self.committed.setdefault(k, 0)
            self.committed[k] += 1
            return {**op, "type": "ok"}

    t = _small(W.g2_test(ops=30), tmp_path)
    t["client"] = BrokenG2Client()
    t["concurrency"] = 6
    result = core.run(t)
    assert result["results"]["valid?"] is False
    assert result["results"]["illegal-count"] >= 1


def test_register_nemesis_builder_shape():
    t = W.register_tester_nemesis(time_limit=1.0)
    assert t["name"] == "register-nemesis"
    from comdb2_tpu.harness import nemesis as N
    assert isinstance(t["nemesis"], N.Partitioner)
