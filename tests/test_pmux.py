"""pmux-style port discovery (round-4 VERDICT Missing #5): the
``ct_pmux`` daemon (the ``tools/pmux`` role), ``sut_node -M``
registration, the native HA client's port-less discovery entries, and
the Python :mod:`comdb2_tpu.control.pmux` client."""

import os
import signal
import socket
import subprocess
import time

import pytest

from comdb2_tpu.control.pmux import PmuxClient, resolve_layout

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUILD = os.path.join(ROOT, "native", "build")
PMUX = os.path.join(BUILD, "ct_pmux")
SUT = os.path.join(BUILD, "sut_node")

pytestmark = pytest.mark.skipif(not os.path.exists(PMUX),
                                reason="ct_pmux not built")


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _await_port(port, deadline_s=10.0):
    end = time.monotonic() + deadline_s
    while time.monotonic() < end:
        s = socket.socket()
        s.settimeout(0.3)
        try:
            if s.connect_ex(("127.0.0.1", port)) == 0:
                return
        finally:
            s.close()
        time.sleep(0.05)
    raise RuntimeError(f"port {port} never came up")


def _spawn_pmux(port, state_file=None, lo=21000, hi=21999):
    args = [PMUX, "-p", str(port), "-r", str(lo), str(hi)]
    if state_file:
        args += ["-f", str(state_file)]
    p = subprocess.Popen(args, stdout=subprocess.DEVNULL,
                         stderr=subprocess.DEVNULL)
    _await_port(port)
    return p


def _kill(p):
    try:
        p.send_signal(signal.SIGKILL)
    except OSError:
        pass
    p.wait()


def test_protocol_roundtrip(tmp_path):
    (port,) = _free_ports(1)
    p = _spawn_pmux(port)
    try:
        with PmuxClient(port=port) as c:
            assert c.hello()
            assert c.get("sut/none") is None
            a = c.reg("sut/alpha")
            assert 21000 <= a <= 21999
            assert c.reg("sut/alpha") == a          # stable
            b = c.reg("sut/beta")
            assert b != a
            c.use("sut/fixed", 23456)
            assert c.get("sut/fixed") == 23456
            used = c.used()
            assert used == {"sut/alpha": a, "sut/beta": b,
                            "sut/fixed": 23456}
            assert c.delete("sut/beta")
            assert c.get("sut/beta") is None
            assert not c.delete("sut/beta")          # already gone
    finally:
        _kill(p)


def test_use_refuses_port_aliasing(tmp_path):
    """Publishing a port another service holds must ERR: deleting
    either alias would free the port under the survivor and a later
    reg would double-assign it."""
    (port,) = _free_ports(1)
    p = _spawn_pmux(port)
    try:
        with PmuxClient(port=port) as c:
            a = c.reg("sut/a")
            with pytest.raises(OSError, match="port in use"):
                c.use("sut/b", a)
            c.use("sut/a", a)          # re-publishing your own is fine
            assert c.used() == {"sut/a": a}
    finally:
        _kill(p)


def test_used_drops_dead_connection_and_redials(tmp_path):
    """A daemon killed between calls must not poison the client: the
    next used() on the stale socket raises OSError, DROPS the
    connection (same contract as _request), and once a daemon is back
    the following call transparently redials."""
    (port,) = _free_ports(1)
    state = tmp_path / "pmux.state"
    p = _spawn_pmux(port, state)
    c = PmuxClient(port=port)
    try:
        a = c.reg("sut/alpha")
        assert c.used() == {"sut/alpha": a}
        _kill(p)                     # daemon dies under the client
        with pytest.raises(OSError):
            c.used()
        assert c._sock is None       # stale connection was dropped
        p = _spawn_pmux(port, state)  # daemon returns with the state
        assert c.used() == {"sut/alpha": a}   # redialed, not wedged
    finally:
        c.close()
        _kill(p)


def test_exit_actually_stops_the_daemon(tmp_path):
    (port,) = _free_ports(1)
    p = _spawn_pmux(port)
    try:
        with PmuxClient(port=port) as c:
            assert c._request("exit").startswith("0")
        p.wait(timeout=5)              # no further connection needed
        assert p.returncode == 0
    finally:
        _kill(p)


def test_protocol_garbage_does_not_crash(tmp_path):
    """Binary garbage, oversized lines, and half-commands must get ERR
    replies (or closed connections) — never a daemon crash."""
    (port,) = _free_ports(1)
    p = _spawn_pmux(port)
    try:
        for payload in (b"\x00\xff\xfe garbage\n", b"reg\n", b"get\n",
                        b"use onlysvc\n", b"del\n",
                        b"A" * 100_000 + b"\n", b"\n"):
            s = socket.create_connection(("127.0.0.1", port), timeout=2)
            s.sendall(payload)
            try:
                r = s.recv(256)
                assert r == b"" or r.startswith(b"-1"), (payload[:20], r)
            finally:
                s.close()
        # still alive and serving
        with PmuxClient(port=port) as c:
            assert c.hello()
        assert p.poll() is None
    finally:
        _kill(p)


def test_concurrent_registrations_never_alias(tmp_path):
    """20 clients registering distinct services concurrently must get
    20 distinct ports (allocation races under the daemon's mutex)."""
    import threading

    (port,) = _free_ports(1)
    p = _spawn_pmux(port)
    got = {}
    lock = threading.Lock()

    def worker(i):
        with PmuxClient(port=port) as c:
            pt = c.reg(f"sut/svc{i}")
        with lock:
            got[i] = pt

    try:
        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(20)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10)
        assert len(got) == 20
        assert len(set(got.values())) == 20, sorted(got.values())
    finally:
        _kill(p)


def test_assignments_persist_across_restart(tmp_path):
    (port,) = _free_ports(1)
    state = tmp_path / "pmux.state"
    p = _spawn_pmux(port, state)
    try:
        with PmuxClient(port=port) as c:
            a = c.reg("sut/durable")
            c.use("sut/pinned", 23999)
    finally:
        _kill(p)
    p = _spawn_pmux(port, state)
    try:
        with PmuxClient(port=port) as c:
            assert c.reg("sut/durable") == a      # same port after boot
            assert c.get("sut/pinned") == 23999
    finally:
        _kill(p)


def test_sut_node_registers_and_python_resolves(tmp_path):
    """sut_node -M publishes its client port; the harness resolves the
    cluster layout by service name instead of port config."""
    if not os.path.exists(SUT):
        pytest.skip("sut_node not built")
    pmux_port, node_port = _free_ports(2)
    pm = _spawn_pmux(pmux_port)
    sn = subprocess.Popen(
        [SUT, "-i", "0", "-n", str(node_port), "-P", "0",
         "-e", "500", "-l", "300",
         "-M", f"{pmux_port}:sut/mydb"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        _await_port(node_port)
        deadline = time.monotonic() + 10
        with PmuxClient(port=pmux_port) as c:
            while c.get("sut/mydb") is None:
                assert time.monotonic() < deadline, "never registered"
                time.sleep(0.1)
        layout = resolve_layout([("127.0.0.1", pmux_port)], "sut/mydb")
        assert layout == [("127.0.0.1", node_port)]
        # the resolved port really serves the SUT protocol
        s = socket.create_connection(layout[0], timeout=2)
        f = s.makefile("rw")
        f.write("P\n")
        f.flush()
        assert f.readline().strip() == "PONG"
        s.close()
    finally:
        _kill(sn)
        _kill(pm)


def test_ct_sql_resolves_via_pmux(tmp_path):
    """ct_sql with a PORT-LESS host entry resolves through pmux (the
    cdb2sql portmux flow) and runs SQL against the discovered node."""
    ct_sql = os.path.join(BUILD, "ct_sql")
    if not (os.path.exists(ct_sql) and os.path.exists(SUT)):
        pytest.skip("native artifacts not built")
    pmux_port, node_port = _free_ports(2)
    pm = _spawn_pmux(pmux_port)
    sn = subprocess.Popen(
        [SUT, "-i", "0", "-n", str(node_port), "-P", "0",
         "-e", "500", "-l", "300",
         "-M", f"{pmux_port}:sut/sqldb"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        _await_port(node_port)
        with PmuxClient(port=pmux_port) as c:
            deadline = time.monotonic() + 10
            while c.get("sut/sqldb") is None:
                assert time.monotonic() < deadline
                time.sleep(0.1)
        env = {**os.environ, "COMDB2_TPU_PMUX_PORT": str(pmux_port)}
        r = subprocess.run(
            [ct_sql, "127.0.0.1", "-s", "sut/sqldb",
             "-c", "insert into register (id, val) values (1, 6)",
             "-c", "select val from register where id = 1"],
            capture_output=True, text=True, env=env, timeout=20)
        assert r.returncode == 0, (r.stdout, r.stderr)
        assert r.stdout.splitlines() == ["ROWS 1", "V 6"], r.stdout
        # unregistered service: clean failure, not a hang
        r2 = subprocess.run(
            [ct_sql, "127.0.0.1", "-s", "sut/none", "-c", "begin"],
            capture_output=True, text=True, env=env, timeout=20)
        assert r2.returncode == 2, (r2.stdout, r2.stderr)
    finally:
        _kill(sn)
        _kill(pm)


def test_native_client_resolves_portless_entry(tmp_path):
    """The native HA client's discovery config may name hosts WITHOUT
    ports; sut_tcp_open then asks that host's pmux (the cdb2api
    portmux flow). Driven through the ctypes shared library."""
    import ctypes

    lib_path = os.path.join(BUILD, "libct_sut.so")
    if not (os.path.exists(lib_path) and os.path.exists(SUT)):
        pytest.skip("native artifacts not built")
    pmux_port, node_port = _free_ports(2)
    pm = _spawn_pmux(pmux_port)
    sn = subprocess.Popen(
        [SUT, "-i", "0", "-n", str(node_port), "-P", "0",
         "-e", "500", "-l", "300",
         "-M", f"{pmux_port}:sut/mydb"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    cfg = tmp_path / "discovery.cfg"
    cfg.write_text("# discovery\nmydb 127.0.0.1\n")
    try:
        _await_port(node_port)
        with PmuxClient(port=pmux_port) as c:
            deadline = time.monotonic() + 10
            while c.get("sut/mydb") is None:
                assert time.monotonic() < deadline
                time.sleep(0.1)
        lib = ctypes.CDLL(lib_path)
        lib.sut_tcp_open.restype = ctypes.c_void_p
        lib.sut_tcp_open.argtypes = [ctypes.c_char_p, ctypes.c_uint]
        lib.sut_tcp_reg_write.restype = ctypes.c_int
        lib.sut_tcp_reg_write.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.sut_tcp_reg_read.restype = ctypes.c_int
        lib.sut_tcp_reg_read.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_int),
                                         ctypes.POINTER(ctypes.c_int)]
        lib.sut_tcp_close.argtypes = [ctypes.c_void_p]
        os.environ["COMDB2_TPU_PMUX_PORT"] = str(pmux_port)
        try:
            t = lib.sut_tcp_open(f"@{cfg}#mydb".encode(), 7)
            assert t, "open through pmux discovery failed"
            assert lib.sut_tcp_reg_write(t, 42) == 0      # SUT_OK
            val = ctypes.c_int(-1)
            found = ctypes.c_int(0)
            assert lib.sut_tcp_reg_read(t, ctypes.byref(val),
                                        ctypes.byref(found)) == 0
            assert found.value and val.value == 42
            lib.sut_tcp_close(t)
            # an unregistered service must fail the open, not hang
            cfg2 = tmp_path / "d2.cfg"
            cfg2.write_text("otherdb 127.0.0.1\n")
            assert not lib.sut_tcp_open(f"@{cfg2}#otherdb".encode(), 7)
        finally:
            os.environ.pop("COMDB2_TPU_PMUX_PORT", None)
    finally:
        _kill(sn)
        _kill(pm)
