"""Streaming-session suite (docs/streaming.md).

The load-bearing contract: a session fed K random-sized deltas must
reach the IDENTICAL final verdict (status + fail index always; final
frontier count on VALID — counts are engine diagnostics on
non-VALID, CLAUDE.md) as one-shot ``check_batch`` on the
concatenated history, across the register / cas / keyed / wide-P
families — while per-append device dispatches cover ONLY the new
segments (counter-asserted on ``stream.engine.DISPATCHES`` and
``pallas_seg.MOSAIC_BUILDS``).

Below the device layer, the incremental ingest/segment passes are
golden-tested BIT-identical to the one-shot pack path — the id
tables, arrays and renamed segment streams a post-hoc re-check would
build.
"""

import random

import numpy as np
import pytest

from comdb2_tpu.checker import linear_jax as LJ
from comdb2_tpu.checker.batch import check_batch, pack_batch
from comdb2_tpu.checker.independent import wrap_keyed_history
from comdb2_tpu.models.memo import IncrementalMemo, memoize_model
from comdb2_tpu.models.model import MODELS
from comdb2_tpu.ops import op as O
from comdb2_tpu.ops.packed import pack_history
from comdb2_tpu.ops.synth import (inject_anomaly, pinned_wide_history,
                                  register_history)
from comdb2_tpu.stream import (SessionManager, StreamIngest,
                               StreamSession)
from comdb2_tpu.stream import engine as ENG

V = {True: 0, False: 1, "unknown": 2}

ARRAYS = ("process", "type", "f", "value", "trans", "pair", "fails",
          "time")
TABLES = ("process_table", "f_table", "value_table",
          "transition_table")


def _keyed_history(rng, n=24):
    h = []
    for _ in range(n):
        k = rng.randrange(3)
        p = rng.randrange(4)
        v = rng.randrange(3)
        h.append(O.invoke(p, "write", (k, v)))
        h.append(O.ok(p, "write", (k, v)))
    return wrap_keyed_history(h)


def _families():
    rng = random.Random(1311)
    yield ("register", "cas-register",
           register_history(rng, n_procs=4, n_events=60, p_info=0.05))
    yield ("cas-bounded", "cas-register",
           register_history(rng, n_procs=6, n_events=60, values=3,
                            max_pending=3))
    yield ("keyed", "cas-register-comdb2", _keyed_history(rng))
    yield ("register-invalid", "cas-register",
           inject_anomaly(register_history(rng, n_procs=4,
                                           n_events=40),
                          "stale-read")[0])


def _oneshot(h, model, F=1024):
    b = pack_batch([pack_history(list(h))], MODELS[model]())
    st, fa, nf = check_batch(b, F=F)
    return int(st[0]), int(fa[0]), int(nf[0])


def _feed(h, model, seed=0, max_delta=13, engine="auto"):
    s = StreamSession(model, engine=engine)
    rng = random.Random(seed)
    i = 0
    while i < len(h):
        k = min(len(h) - i, rng.randint(1, max_delta))
        s.append(h[i:i + k])
        i += k
    out = s.finalize_input()
    return s, out


def _assert_verdict(exp, out):
    got = (V[out["valid"]], out["op_index"], out["final_count"])
    assert exp[0] == got[0] and exp[1] == got[1], (exp, got)
    if exp[0] == 0:            # counts compare on VALID only
        assert exp[2] == got[2], (exp, got)


# --- bit parity below the device layer -------------------------------------

@pytest.mark.parametrize("name,model,h",
                         list(_families()),
                         ids=lambda x: x if isinstance(x, str) else "")
def test_ingest_bit_parity(name, model, h):
    """The incremental pack's settled columns/tables are BIT-identical
    to the one-shot columnar pack of the full history."""
    packed = pack_history(list(h))
    ing = StreamIngest()
    rng = random.Random(7)
    i = 0
    while i < len(h):
        k = min(len(h) - i, rng.randint(1, 9))
        ing.append(h[i:i + k])
        i += k
    ing.finalize()
    got = ing.packed_history()
    for a in ARRAYS:
        np.testing.assert_array_equal(
            getattr(got, a), getattr(packed, a), err_msg=f"{name}.{a}")
    for t in TABLES:
        assert getattr(got, t) == getattr(packed, t), f"{name}.{t}"


@pytest.mark.parametrize("name,model,h",
                         list(_families()),
                         ids=lambda x: x if isinstance(x, str) else "")
def test_segment_bit_parity(name, model, h):
    """Incremental segmentation + carried slot renaming reproduce the
    one-shot ``make_segments`` + ``remap_slots`` stream bit-for-bit
    (modulo K padding width)."""
    packed = pack_history(list(h))
    segs = LJ.make_segments(packed)
    renamed, p_eff = LJ.remap_slots(segs)
    s = StreamSession(model)
    rng = random.Random(11)
    i = 0
    while i < len(h):
        k = min(len(h) - i, rng.randint(1, 9))
        s.append(h[i:i + k])
        i += k
    s.finalize_input()
    S = renamed.ok_proc.shape[0]
    assert s.seg.n_segments == S
    assert s.seg.p_eff == p_eff
    K = max(renamed.inv_proc.shape[1], s.seg.k_max)
    ip, it, okp, dp = s.seg.padded(0, S, S, K)
    np.testing.assert_array_equal(
        ip, np.pad(renamed.inv_proc,
                   ((0, 0), (0, K - renamed.inv_proc.shape[1])),
                   constant_values=-1))
    np.testing.assert_array_equal(okp, renamed.ok_proc)
    np.testing.assert_array_equal(dp, renamed.depth)
    np.testing.assert_array_equal(s.seg.seg_row.a[:S],
                                  segs.seg_index)


def test_incremental_memo_matches_oneshot():
    """Extension-grown memo covers the same reachable state set with
    the same successor structure as a one-shot memoization at the
    final (transitions, depth) — state NUMBERING may differ, so the
    comparison maps through the model objects."""
    model = MODELS["cas-register"]()
    transitions = [("write", 1), ("write", 2), ("read", 1),
                   ("cas", (1, 2)), ("read", None), ("write", 3)]
    one = memoize_model(model, transitions, max_depth=5)
    inc = IncrementalMemo(model)
    inc.extend(transitions[:2], 1)
    inc.extend(transitions[2:4], 2)
    inc.extend(transitions[4:], 5)
    assert inc.n_states == one.n_states
    assert inc.transitions == one.transitions
    to_one = {id(m): one.states.index(m) for m in inc.states}
    for i, m in enumerate(inc.states):
        j = to_one[id(m)]
        for t in range(len(transitions)):
            a = int(inc.succ[i, t])
            b = int(one.succ[j, t])
            if a < 0 or b < 0:
                assert a == b == -1 or \
                    (a < 0) == (b < 0), (i, t, a, b)
            else:
                assert one.states[b] == inc.states[a]


# --- device-layer parity ---------------------------------------------------

@pytest.mark.parametrize("name,model,h",
                         list(_families()),
                         ids=lambda x: x if isinstance(x, str) else "")
def test_delta_verdict_parity(name, model, h):
    exp = _oneshot(h, model)
    _s, out = _feed(h, model, seed=3)
    _assert_verdict(exp, out)


def test_wide_p_parity_rides_mxu():
    """The wide-P family: concurrency growth re-routes the session to
    the MXU rung mid-stream (replay), and the final verdict still
    matches one-shot."""
    h = pinned_wide_history(18)
    exp = _oneshot(h, "cas-register")
    s, out = _feed(h, "cas-register", seed=5, max_delta=23)
    _assert_verdict(exp, out)
    assert out["engine"] == "mxu"
    assert out["replays"] >= 1          # growth re-routes happened


def test_invalid_latches_without_dispatch():
    h, _ = inject_anomaly(
        register_history(random.Random(2), n_procs=3, n_events=30),
        "stale-read")
    s, out = _feed(h, "cas-register", seed=2)
    assert out["valid"] is False
    d0 = s.dispatches
    e0 = ENG.DISPATCHES
    r = s.append(h[:8])
    assert r["valid"] is False and r.get("latched")
    assert s.dispatches == d0 and ENG.DISPATCHES == e0


def test_escalation_mid_session_resumes_in_place():
    """A concurrency burst overflows the first frontier rung: the
    session widens the PRE-delta carry (expand_seg_carry) and re-runs
    only the delta — verdict unchanged vs one-shot."""
    h = []
    for p in range(8):
        h.append(O.invoke(p, "write", p))
    for p in range(8):
        h.append(O.ok(p, "write", p))
    h += [O.invoke(0, "read", None), O.ok(0, "read", 7)]
    # the burst's frontier exceeds 1024: give the one-shot the
    # session ladder's eventual budget or IT answers UNKNOWN where
    # the session escalated through to a verdict
    exp = _oneshot(h, "cas-register", F=8192)
    s = StreamSession("cas-register", engine="xla")
    s.append(h[:9])
    s.append(h[9:])
    out = s.finalize_input()
    _assert_verdict(exp, out)
    assert out["frontier_capacity"] > ENG.STREAM_CAPACITIES[0]
    assert out["replays"] == 0          # in place, not a replay


def test_per_append_work_is_o_delta():
    """Dispatch counters: every same-sized append costs the SAME
    number of delta dispatches no matter how much history the session
    has accumulated, and no Mosaic program is (re)built per append."""
    from comdb2_tpu.checker import pallas_seg as PSEG

    # bounded in-flight: the frontier stays small, so no append needs
    # a capacity escalation and the counter isolates the O(delta)
    # claim (escalations are legitimate EXTRA dispatches, tested
    # separately)
    h = register_history(random.Random(4), n_procs=3, n_events=240,
                         values=2, p_info=0.0, max_pending=2)
    s = StreamSession("cas-register", engine="xla")
    per_append = []
    m0 = PSEG.MOSAIC_BUILDS
    for i in range(0, len(h), 24):
        d0 = ENG.DISPATCHES
        s.append(h[i:i + 24])
        per_append.append(ENG.DISPATCHES - d0)
    assert PSEG.MOSAIC_BUILDS == m0
    # every append fits one delta_pad bucket -> AT MOST one dispatch,
    # first append to last — per-append cost never grows with the
    # accumulated history (a 0 is an append whose rows were held by
    # the watermark and dispatched with the next delta)
    assert max(per_append) == 1, per_append
    assert sum(per_append) >= len(per_append) - 2, per_append
    out = s.finalize_input()
    assert out["valid"] is True


# --- sessions as a service surface -----------------------------------------

def _mgr_clock():
    from comdb2_tpu.obs.trace import monotonic

    return monotonic()


def test_manager_cap_and_eviction():
    mgr = SessionManager(max_sessions=2, idle_s=10.0)
    now = _mgr_clock()
    sid1, s1 = mgr.open(now)
    sid2, _s2 = mgr.open(now + 1)
    from comdb2_tpu.stream.manager import SessionLimit

    with pytest.raises(SessionLimit):
        mgr.open(now + 2)
    s1.append([O.invoke(0, "write", 1), O.ok(0, "write", 1)])
    assert mgr.carry_bytes() > 0
    # sid1 idles out; sid2 was touched later. Eviction is
    # checkpoint-not-replay (round 12): the carry frees, the host
    # checkpoint stays, and the next get() restores transparently
    mgr.get(sid2, now + 9)
    evicted = mgr.evict_idle(now + 12)
    assert evicted == [sid1]
    assert len(mgr) == 1 and mgr.checkpoint_count() == 1
    assert mgr.evictions == 1
    restored = mgr.get(sid1, now + 13)
    assert restored is not None and mgr.restores == 1
    out = restored.append([O.invoke(1, "read", None),
                           O.Op(1, "ok", "read", 1)])
    assert out["valid"] is True and out["checked_through"] == 4


def test_eviction_forces_inflight_finalize():
    """evict_idle must push a staged-but-unfinalized append through
    its (idempotent) finalize before dropping the carry — a
    ring-resident dispatch finalizing against a released engine
    would report a confusing engine error instead of a verdict."""
    mgr = SessionManager(max_sessions=4, idle_s=10.0)
    now = _mgr_clock()
    sid, s = mgr.open(now)
    fin = s.append_stage([O.invoke(0, "write", 1),
                          O.ok(0, "write", 1)])
    assert mgr.evict_idle(now + 11) == [sid]
    out = fin()                         # cached by the forced pass
    assert out["valid"] is True and out["checked_through"] == 2


def test_follow_reads_unterminated_final_line(tmp_path):
    """A history file whose last line lacks a trailing newline (the
    writer died) still contributes its final op — here the violating
    read — once the idle timeout declares the stream over."""
    from comdb2_tpu import filetest
    from comdb2_tpu.ops.history import history_to_edn

    h = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
         O.invoke(1, "read", None), O.Op(1, "ok", "read", 9)]
    p = tmp_path / "hist.edn"
    p.write_text(history_to_edn(h))     # no trailing newline
    rc = filetest.main([str(p), "--follow", "--follow-idle", "0.5",
                        "--follow-poll", "0.05"])
    assert rc == 1


def test_service_stream_verbs_end_to_end():
    """open -> append (clean) -> append (violating: latches) -> poll
    -> close through the REAL admission plane: slots, launch
    reasons, the ring, stages tiling latency_ms."""
    from comdb2_tpu.obs import trace as obs
    from comdb2_tpu.service.core import VerifierCore

    core = VerifierCore(batch_cap=4, max_sessions=2,
                        session_idle_s=60.0)
    launches0 = sum(core.m[k] for k in
                    ("launch_full", "launch_deadline", "launch_idle"))
    _, r = core.submit({"kind": "stream", "verb": "open", "id": 1},
                       obs.monotonic())
    assert r["ok"], r
    sid = r["session"]
    h_ok = [O.invoke(0, "write", 1), O.ok(0, "write", 1)]
    h_bad = [O.invoke(1, "read", None), O.Op(1, "ok", "read", 9)]
    from comdb2_tpu.ops.history import history_to_edn

    p, r = core.submit({"kind": "stream", "verb": "append", "id": 2,
                        "session": sid,
                        "history": history_to_edn(h_ok)},
                       obs.monotonic())
    assert p is not None and r is None
    (p, rep), = core.tick()
    assert rep["valid"] is True and rep["kind"] == "stream"
    # stages tile latency_ms like every other reply (expiries incl.)
    assert abs(sum(rep["stages"].values()) - rep["latency_ms"]) < 1.0
    p, r = core.submit({"kind": "stream", "verb": "append", "id": 3,
                        "session": sid,
                        "history": history_to_edn(h_bad)},
                       obs.monotonic())
    (p, rep), = core.tick()
    assert rep["valid"] is False
    # latched appends answer at submit, no queue, still counted
    _, r = core.submit({"kind": "stream", "verb": "append", "id": 4,
                        "session": sid,
                        "history": history_to_edn(h_ok)},
                       obs.monotonic())
    assert r is not None and r["latched"] and r["valid"] is False
    _, r = core.submit({"kind": "stream", "verb": "poll", "id": 5,
                        "session": sid}, obs.monotonic())
    assert r["valid"] is False
    _, r = core.submit({"kind": "stream", "verb": "close", "id": 6,
                        "session": sid}, obs.monotonic())
    assert r["ok"] and len(core.sessions) == 0
    # launch_* reasons cover stream appends
    launches = sum(core.m[k] for k in
                   ("launch_full", "launch_deadline", "launch_idle"))
    assert launches >= launches0 + 2
    assert core.m["stream_appends"] == 3
    # the metrics plane carries the session gauges
    mr = core.metrics_reply()
    assert "stream_sessions_active" in mr["prometheus"]
    assert "stream_carry_resident_bytes" in mr["prometheus"]


def test_service_session_cap_overloads_with_hint():
    from comdb2_tpu.obs import trace as obs
    from comdb2_tpu.service.core import VerifierCore

    core = VerifierCore(max_sessions=1)
    _, r1 = core.submit({"kind": "stream", "verb": "open", "id": 1},
                        obs.monotonic())
    _, r2 = core.submit({"kind": "stream", "verb": "open", "id": 2},
                        obs.monotonic())
    assert r1["ok"]
    assert not r2["ok"] and r2["error"] == "overload"
    assert r2["retry_after_ms"] > 0


def test_service_unknown_session_is_bad_request():
    from comdb2_tpu.obs import trace as obs
    from comdb2_tpu.service.core import VerifierCore

    core = VerifierCore()
    _, r = core.submit({"kind": "stream", "verb": "append", "id": 1,
                        "session": "nope", "history": "{}"},
                       obs.monotonic())
    assert not r["ok"] and r["error"] == "bad-request"


def test_compile_guard_closed_over_mixed_workload():
    """The acceptance gate: mixed stream + one-shot traffic in one
    process stays inside the declared inventory (stream-delta site +
    the batch sites)."""
    from comdb2_tpu.utils import compile_guard

    with compile_guard.guard() as g:
        # direct check_batch callers own the pow2 batch pad (the
        # service pads for them): 4 histories, a declared B rung
        hs = [register_history(random.Random(s), n_procs=3,
                               n_events=24) for s in range(4)]
        b = pack_batch([pack_history(x) for x in hs],
                       MODELS["cas-register"]())
        check_batch(b, F=256)
        s = StreamSession("cas-register")
        h = register_history(random.Random(9), n_procs=3, n_events=40)
        for i in range(0, len(h), 7):
            s.append(h[i:i + 7])
        s.finalize_input()
    g.assert_closed()


def test_info_before_invoke_does_not_retire_it():
    """An invoke AFTER an :info row of the same process is a live
    pending call (one-shot ``complete`` allows it) — the info must
    not resolve it, or its ok's value back-fill never reaches the
    interned tables and the bit parity with the one-shot pack
    breaks."""
    d1 = [O.info(0, "write", None),
          O.invoke(0, "write", None),
          O.invoke(1, "write", 5)]
    d2 = [O.ok(0, "write", 7), O.ok(1, "write", 5)]
    ing = StreamIngest()
    lo, hi = ing.append(d1)
    assert hi == 1                      # rows 1-2 blocked: unresolved
    ing.append(d2)
    ing.finalize()
    packed = pack_history(d1 + d2)
    got = ing.packed_history()
    for a in ARRAYS:
        np.testing.assert_array_equal(getattr(got, a),
                                      getattr(packed, a), err_msg=a)
    for t in TABLES:
        assert getattr(got, t) == getattr(packed, t), t


def test_fail_value_mismatch_leaves_ingest_untouched():
    """The fail-pair value check validates BEFORE any column mutates
    (StreamIngest is public API — a half-applied delta would corrupt
    every later view)."""
    from comdb2_tpu.stream import MalformedDelta

    ing = StreamIngest()
    ing.append([O.invoke(0, "write", 1)])
    n0 = len(ing)
    with pytest.raises(MalformedDelta):
        ing.append([O.fail(0, "write", 2)])   # 2 != invoked 1
    assert len(ing) == n0
    # the ingest still works after the rejected delta
    lo, hi = ing.append([O.ok(0, "write", 1)])
    assert hi == 2


def test_concurrency_past_the_ladder_latches_unknown():
    """A crash-heavy history pinning > STREAM_MAX_P slots has no
    declared program to run — the session latches UNKNOWN instead of
    compiling off-inventory (one per growth step)."""
    h = pinned_wide_history(ENG.STREAM_MAX_P + 2, with_reads=False)
    s = StreamSession("cas-register")
    out = None
    for i in range(0, len(h), 16):
        out = s.append(h[i:i + 16])
    out = s.finalize_input()
    assert out["valid"] == "unknown"
    assert "stream ladder" in out["cause"]


def test_malformed_delta_latches_unknown():
    s = StreamSession("cas-register")
    out = s.append([O.invoke(0, "write", 1), O.invoke(0, "write", 2)])
    assert out["valid"] == "unknown"
    assert "malformed" in out["cause"]
    # latched thereafter
    r = s.append([O.invoke(1, "write", 1)])
    assert r["valid"] == "unknown" and r.get("latched")


def test_append_finalize_is_idempotent():
    """The service's batch finish() calls every staged fin, but a
    later append staged in the same batch already forced the earlier
    one through the session's inflight serialization — the second
    call must be a no-op returning the same verdict, never a re-run
    of _finalize_range against the later delta's carry."""
    h = register_history(random.Random(6), n_procs=3, n_events=60,
                         p_info=0.0, max_pending=2)
    exp = _oneshot(h, "cas-register")
    s = StreamSession("cas-register")
    cut = len(h) // 2
    fin1 = s.append_stage(h[:cut])
    fin2 = s.append_stage(h[cut:])      # forces fin1 internally
    d0 = s.dispatches
    r1a = fin1()                        # second call: cached
    r1b = fin1()
    assert s.dispatches == d0 and r1a == r1b
    fin2()
    out = s.finalize_input()
    _assert_verdict(exp, out)


def test_two_appends_one_batch_through_the_service():
    """Two appends to ONE session coalesce into one shape-class slot
    and finalize through one ring entry — verdict parity end to end."""
    from comdb2_tpu.obs import trace as obs
    from comdb2_tpu.ops.history import history_to_edn
    from comdb2_tpu.service.core import VerifierCore

    h = register_history(random.Random(8), n_procs=3, n_events=48,
                         p_info=0.0, max_pending=2)
    exp = _oneshot(h, "cas-register")
    core = VerifierCore(batch_cap=8)
    _, r = core.submit({"kind": "stream", "verb": "open", "id": 1},
                       obs.monotonic())
    sid = r["session"]
    cut = len(h) // 2
    now = obs.monotonic()
    core.submit({"kind": "stream", "verb": "append", "id": 2,
                 "session": sid, "history": history_to_edn(h[:cut])},
                now)
    core.submit({"kind": "stream", "verb": "append", "id": 3,
                 "session": sid, "history": history_to_edn(h[cut:])},
                now)
    done = core.tick()
    assert len(done) == 2
    for _p, rep in done:
        assert rep["valid"] is True, rep
    _, r = core.submit({"kind": "stream", "verb": "close", "id": 4,
                        "session": sid}, obs.monotonic())
    _assert_verdict(exp, r)


@pytest.fixture()
def interpret_kernel():
    from comdb2_tpu.checker import pallas_seg as PS

    PS.use_interpret(True)
    PS.available.cache_clear()      # pick_rung probes through it
    yield
    PS.use_interpret(False)
    PS.available.cache_clear()


def test_kernel_rung_stride_and_table_growth(interpret_kernel):
    """The kernel rung end to end (exact kernel as XLA ops): a
    NON-pow2 transition count exercises the bucketed-stride table
    packing (the padded table must match the rung's declared nt), and
    a delta that interns a new transition WITHIN the same pow2 bucket
    exercises the memo.version-keyed table cache — a stale table
    misdecodes every later successor."""
    h1 = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
          O.invoke(1, "write", 2), O.ok(1, "write", 2),
          O.invoke(0, "read", None), O.ok(0, "read", 2)]
    h2 = [O.invoke(1, "write", 3), O.ok(1, "write", 3),  # 4th trans,
          O.invoke(0, "read", None), O.ok(0, "read", 3)]  # same bucket
    h3 = [O.invoke(0, "read", None), O.ok(0, "read", 1)]  # stale read
    exp = _oneshot(h1 + h2 + h3, "cas-register")
    s = StreamSession("cas-register")
    o1 = s.append(h1)
    assert s._rung == "kernel"
    o2 = s.append(h2)
    o3 = s.append(h3)
    out = s.finalize_input()
    assert (o1["valid"], o2["valid"], o3["valid"]) == (True, True,
                                                      False)
    _assert_verdict(exp, out)
    assert out["engine"] == "kernel"


def test_unresolved_invokes_hold_the_watermark():
    """An ok whose earlier invoke is still open can't be checked yet
    (its value back-fill may arrive later): checked_through stalls at
    the unresolved invoke, then catches up."""
    s = StreamSession("cas-register")
    out = s.append([O.invoke(0, "read", None),        # unresolved
                    O.invoke(1, "write", 1),
                    O.ok(1, "write", 1)])
    assert out["checked_through"] == 0
    assert out["dispatches"] == 0
    out = s.append([O.ok(0, "read", 1)])              # resolves
    assert out["checked_through"] == 4
    assert out["valid"] is True
