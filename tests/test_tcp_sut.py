"""End-to-end distributed loop: harness → TCP → native sut_server,
with SIGSTOP faults producing indeterminate ops."""

import os
import signal
import socket
import subprocess
import time

import pytest

from comdb2_tpu.checker import checkers as C
from comdb2_tpu.checker import independent as I
from comdb2_tpu.harness import core, fake
from comdb2_tpu.harness import generator as G
from comdb2_tpu.models import model as M
from comdb2_tpu.workloads import comdb2 as W
from comdb2_tpu.workloads.tcp import TcpRegisterClient, spawn_server

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BINARY = os.path.join(ROOT, "native", "build", "sut_server")

pytestmark = pytest.mark.skipif(not os.path.exists(BINARY),
                                reason="sut_server not built")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture
def server():
    port = _free_port()
    proc = spawn_server(BINARY, port)
    yield port, proc
    proc.kill()
    proc.wait()


def _tcp_test(tmp_path, port, **kw):
    t = fake.noop_test()
    t.update({
        "nodes": [], "concurrency": 5, "name": "tcp-register",
        "store-root": str(tmp_path / "store"),
        "client": TcpRegisterClient(port=port, timeout_s=0.5),
        "model": M.cas_register(),
        "generator": G.clients(G.limit(
            100, G.mix([W.r, W.w, W.cas]))),
        # host engine: this is a harness E2E test; device compiles for
        # the odd shapes here would dominate suite time
        "checker": I.checker(C.Linearizable(backend="host")),
    })
    t.update(kw)
    return t


def test_tcp_register_run_valid(tmp_path, server):
    port, _proc = server
    result = core.run(_tcp_test(tmp_path, port))
    assert result["results"]["valid?"] is True, result["results"]
    oks = [op for op in result["history"] if op.type == "ok"]
    assert len(oks) >= 50


def test_tcp_register_sigstop_yields_info_ops(tmp_path, server):
    """SIGSTOP the server mid-run: requests time out, workers record
    info ops and retire processes, and the history stays linearizable
    once the server resumes."""
    port, proc = server

    class Stopper(fake.client_ns.Client):
        def invoke(self, test, op):
            if op["f"] == "start":
                proc.send_signal(signal.SIGSTOP)
            else:
                proc.send_signal(signal.SIGCONT)
            return dict(op)

    t = _tcp_test(
        tmp_path, port,
        nemesis=Stopper(),
        generator=G.nemesis(
            G.seq([G.sleep(0.2), {"type": "info", "f": "start"},
                   G.sleep(1.2), {"type": "info", "f": "stop"}]),
            G.stagger(0.01, G.limit(120, G.mix([W.r, W.w, W.cas])))))
    result = core.run(t)
    assert result["results"]["valid?"] is True, result["results"]
    infos = [op for op in result["history"]
             if op.type == "info" and op.process != "nemesis"]
    assert infos, "SIGSTOP window should have produced timeouts"


def test_tcp_buggy_server_detected(tmp_path):
    """The negative control over the wire: a buggy server must be
    flagged invalid by the checker. The bug fires deterministically
    (every 4th roll per connection), but *detection* depends on op
    interleaving and which ops land on which connection — retry a few
    rounds so thread-timing variance can't flake the test."""
    for attempt in range(3):
        port = _free_port()
        proc = spawn_server(BINARY, port, "-B")
        try:
            t = _tcp_test(tmp_path, port, name=f"tcp-buggy-{attempt}")
            t["generator"] = G.clients(
                G.limit(250, G.mix([W.r, W.r, W.w, W.cas])))
            result = core.run(t)
            if result["results"]["valid?"] is False:
                return
        finally:
            proc.kill()
            proc.wait()
    raise AssertionError(
        "buggy server never produced a detectable violation in 3 runs")
