"""Streaming wl sessions (ISSUE 20): bank / sets rungs.

Stream verdicts bit-agree with the one-shot ``check_wl_batch`` on
valid + violation twins, appends dispatch O(delta) (counter-asserted),
megabatched advances are bit-identical to solo (verdicts AND carry
bits), verdicts latch, checkpoints round-trip through host numpy, and
the SessionManager evict/restore path preserves all of it.
"""

import numpy as np
import pytest

from comdb2_tpu.checker import wl as W
from comdb2_tpu.ops.op import invoke, ok
from comdb2_tpu.stream import engine as SE
from comdb2_tpu.stream import wl as SW
from comdb2_tpu.stream.manager import SessionManager


# --- bank -------------------------------------------------------------------

def test_bank_stream_matches_one_shot():
    for viol in (None, "total", "n"):
        hists, model = W.bank_batch(7, 3, violation=viol)
        one = W.check_wl_batch(hists, "bank", model)
        for h, o in zip(hists, one):
            s = SW.make_session("wl-bank", model)
            d0 = SE.DISPATCHES
            third = len(h) // 3
            for part in (h[:third], h[third:2 * third],
                         h[2 * third:]):
                s.append(part)
            nd = SE.DISPATCHES - d0
            out = s.close()
            assert out["valid"] == o["valid?"], (viol, out, o)
            if viol in ("total", "n"):
                assert out["valid"] is False
                assert out["op_index"] == max(
                    i for i, op in enumerate(h)
                    if op.type == "ok" and op.f == "read"), out
                kind = "wrong-n" if viol == "n" else "wrong-total"
                assert out["cause"] == f"{kind} read", out
            # O(delta): at most one dispatch per nonempty delta
            assert nd <= 3, nd


def test_bank_snapshot_plane_stream():
    hists, model = W.bank_batch(9, 2, violation="snapshot")
    for h in hists:
        s = SW.make_session("wl-bank", model)
        s.append(h)
        out = s.close()
        assert out["valid"] is True, out
        assert out["snapshot_inconsistent"] >= 1, out


def test_bank_megabatch_bit_parity():
    hists, model = W.bank_batch(11, 6)
    solo = []
    for h in hists:
        s = SW.make_session("wl-bank", model)
        fin = s.append_stage(h)
        solo.append((fin(), np.asarray(s._balance).copy()))
        s.close()

    d0, m0 = SE.DISPATCHES, SE.MEGABATCHES
    sess = [SW.make_session("wl-bank", model) for _ in hists]
    coll = SE.MegaBatch()
    fins = [s.append_stage(h, collector=coll)
            for s, h in zip(sess, hists)]
    coll.flush()
    assert SE.DISPATCHES - d0 == 1, "6 lanes must fuse to one program"
    assert SE.MEGABATCHES - m0 == 1
    assert coll.fused_launches == 1 and coll.fused_lanes == 6
    for s, fin, (so, sbal) in zip(sess, fins, solo):
        fo = fin()
        assert fo["valid"] == so["valid"]
        assert fo["snapshot_inconsistent"] == so["snapshot_inconsistent"]
        assert np.array_equal(np.asarray(s._balance), sbal), \
            "fused carry must be bit-identical to solo"
        s.close()


def test_bank_latch():
    hists, model = W.bank_batch(13, 1, violation="total")
    s = SW.make_session("wl-bank", model)
    s.append(hists[0])
    d0 = SE.DISPATCHES
    out = s.append(hists[0][:4])
    assert out["valid"] is False and out.get("latched") is True, out
    assert SE.DISPATCHES == d0, "latched append must not dispatch"


def test_bank_checkpoint_restore():
    hists, model = W.bank_batch(17, 1)
    h = hists[0]
    s = SW.make_session("wl-bank", model)
    s.append(h[:len(h) // 2])
    ck = s.checkpoint()
    assert ck["wl_family"] == "bank"
    assert isinstance(ck["balance"], np.ndarray), \
        "checkpoints are host numpy only"
    s2 = SW.restore_session(ck)
    s.append(h[len(h) // 2:])
    s2.append(h[len(h) // 2:])
    o1, o2 = s.close(), s2.close()
    assert o1["valid"] is True and o2["valid"] is True
    assert o1["op_count"] == o2["op_count"]


def test_bank_oversized_append_chunks():
    """An append past the WL_DELTA_PADS top rung dispatches in
    sequential solo chunks — same verdict, no open-ended program."""
    hists, model = W.bank_batch(50, 1, n_transfers=100, n_reads=80)
    one = W.check_wl_batch(hists, "bank", model)
    s = SW.make_session("wl-bank", model)
    d0 = SE.DISPATCHES
    s.append(hists[0])
    nd = SE.DISPATCHES - d0
    out = s.close()
    assert out["valid"] == one[0]["valid?"]
    assert nd >= 2, nd


# --- sets -------------------------------------------------------------------

def test_sets_stream_matches_one_shot():
    for viol in (None, "lost", "phantom"):
        hists = W.sets_batch(5, 3, violation=viol)
        one = W.check_wl_batch(hists, "sets")
        for h, o in zip(hists, one):
            s = SW.make_session("wl-sets")
            half = len(h) // 2
            r1 = s.append(h[:half])
            assert r1["valid"] is True, \
                "sets must stay provisional mid-stream"
            s.append(h[half:])
            out = s.close()
            assert out["valid"] == o["valid?"], (viol, out, o)


def test_sets_never_read_unknown():
    s = SW.make_session("wl-sets")
    h = W.sets_batch(6, 1)[0]
    s.append([op for op in h if op.f != "read"])
    out = s.close()
    assert out["valid"] == "unknown", out
    assert out["cause"] == "Set was never read", out


def test_sets_malformed_read_latches_unknown():
    s = SW.make_session("wl-sets")
    s.append([ok(0, "read", "abc")])
    out = s.poll()
    assert out["valid"] == "unknown" and "malformed" in out["cause"]


def test_sets_escalation_in_place():
    s = SW.make_session("wl-sets")
    ops = []
    for v in range(300):
        ops.append(invoke(v, "add", v))
        ops.append(ok(v, "add", v))
    s.append(ops[:100])
    assert s.e_pad == 128
    s.append(ops[100:])
    assert s.e_pad == 1024, "element universe must climb the rung"
    assert s.escalations == 1
    s.append([ok(301, "read", tuple(range(300)))])
    out = s.close()
    assert out["valid"] is True, out


def test_sets_megabatch_bit_parity():
    hists = W.sets_batch(21, 4)
    solo = []
    for h in hists:
        s = SW.make_session("wl-sets")
        s.append(h)
        solo.append((s.poll(), np.asarray(s._fr).copy()))
        s.close()
    d0, m0 = SE.DISPATCHES, SE.MEGABATCHES
    sess = [SW.make_session("wl-sets") for _ in hists]
    coll = SE.MegaBatch()
    fins = [s.append_stage(h, collector=coll)
            for s, h in zip(sess, hists)]
    coll.flush()
    assert SE.DISPATCHES - d0 == 1 and SE.MEGABATCHES - m0 == 1
    for s, fin, (so, sfr) in zip(sess, fins, solo):
        fo = fin()
        assert (fo["lost"], fo["unexpected"]) == \
            (so["lost"], so["unexpected"])
        assert np.array_equal(np.asarray(s._fr), sfr), "carry bits"
        s.close()


def test_sets_checkpoint_restore():
    h = W.sets_batch(30, 1)[0]
    s = SW.make_session("wl-sets")
    s.append(h[:20])
    ck = s.checkpoint()
    s2 = SW.restore_session(ck)
    assert s2._ids == s._ids, "interning table must survive verbatim"
    s.append(h[20:])
    s2.append(h[20:])
    o1, o2 = s.close(), s2.close()
    assert o1["valid"] == o2["valid"], (o1, o2)
    assert o1["lost"] == o2["lost"]


# --- manager integration ----------------------------------------------------

def test_manager_open_evict_restore_close():
    mgr = SessionManager(max_sessions=4, idle_s=10.0)
    hists, model = W.bank_batch(40, 1)
    sid, s = mgr.open(0.0, model="wl-bank", wl=model)
    s.append(hists[0][:6])
    mgr.evict_idle(100.0)
    assert len(mgr) == 0 and mgr.checkpoint_count() == 1, \
        "idle eviction is checkpoint-not-replay"
    s2 = mgr.get(sid, 101.0)
    assert s2 is not None and s2.family == "bank"
    s2.append(hists[0][6:])
    out = mgr.close(sid)
    assert out["valid"] is True, out


def test_bad_model_params():
    with pytest.raises(ValueError):
        SW.make_session("wl-bank")        # bank needs {'n','total'}
    with pytest.raises(ValueError):
        SW.make_session("wl-nope")
