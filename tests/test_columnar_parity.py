"""Golden parity: the columnar ingest is BIT-IDENTICAL to the per-op
packer on every fuzz-corpus family.

The columnar rebuild (ops/columnar.py, the vectorized
``make_segments``, ``remap_slots_batch``) replaces the per-op host
walk that cost ``host_pack_s = 278.2`` at the 4096x bench shape. Its
contract is exact equality — same arrays, same table orders, same
segment streams, same renamed slots, same PackPlan words — because
UNKNOWN-verdict comparability across engines and releases depends on
the key layout, and a packer that merely "agreed on verdicts" could
silently shift fail indices and frontier contents.

Families: register/cas (incl. p10 + max_pending), keyed, wide-P
pinned, crash-heavy with ``:info`` slot pinning, and the txn
list-append histories — plus the seeded anomaly fixtures.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from comdb2_tpu.checker import linear_jax as LJ
from comdb2_tpu.checker.independent import wrap_keyed_history
from comdb2_tpu.ops import op as O
from comdb2_tpu.ops.columnar import pack_history_columnar
from comdb2_tpu.ops.packed import pack_history, pack_history_legacy
from comdb2_tpu.ops.synth import (list_append_history, pinned_wide_history,
                                  register_history, txn_anomaly_history)

ARRAYS = ("process", "type", "f", "value", "trans", "pair", "fails",
          "time")
TABLES = ("process_table", "f_table", "value_table",
          "transition_table")


def _keyed_history(rng):
    h = []
    for _ in range(30):
        k = rng.randrange(3)
        p = rng.randrange(4)
        v = rng.randrange(3)
        h.append(O.invoke(p, "write", (k, v)))
        h.append(O.ok(p, "write", (k, v)))
    return wrap_keyed_history(h)


def _families():
    rng = random.Random(606)
    yield "register", register_history(rng, n_procs=5, n_events=300,
                                       values=5, p_info=0.0)
    yield "cas-p10", register_history(rng, n_procs=10, n_events=300,
                                      values=5, p_info=0.0,
                                      max_pending=5)
    yield "crash-heavy", register_history(rng, n_procs=4, n_events=300,
                                          values=3, p_info=0.3)
    yield "keyed", _keyed_history(rng)
    yield "wide-p-pinned", pinned_wide_history(18)
    yield "txn-list-append", list_append_history(rng, n_procs=3,
                                                 n_txns=40)
    for kind in ("clean", "g0", "g1c", "g1a", "g2-item", "duplicate"):
        yield f"txn-{kind}", txn_anomaly_history(kind)


FAMILIES = list(_families())


def _assert_packed_equal(a, b, ctx):
    for f in ARRAYS:
        x, y = getattr(a, f), getattr(b, f)
        assert x.dtype == y.dtype, (ctx, f, x.dtype, y.dtype)
        assert np.array_equal(x, y), (ctx, f)
    for f in TABLES:
        assert getattr(a, f) == getattr(b, f), (ctx, f)


def _assert_stream_equal(a, b, ctx):
    for f in a._fields:
        x, y = getattr(a, f), getattr(b, f)
        assert x.dtype == y.dtype, (ctx, f, x.dtype, y.dtype)
        assert x.shape == y.shape, (ctx, f, x.shape, y.shape)
        assert np.array_equal(x, y), (ctx, f)


@pytest.mark.parametrize("name,hist", FAMILIES,
                         ids=[n for n, _ in FAMILIES])
def test_pack_bit_identical(name, hist):
    legacy = pack_history_legacy(hist)
    col = pack_history_columnar(hist)
    _assert_packed_equal(legacy, col, name)
    # the lazy .ops view materializes the SAME completed indexed list
    assert col.ops == legacy.ops


@pytest.mark.parametrize("name,hist", FAMILIES,
                         ids=[n for n, _ in FAMILIES])
def test_segments_and_remap_bit_identical(name, hist):
    packed = pack_history(hist)
    for s_pad, k_pad in ((None, None), (64, 8)):
        a = LJ.make_segments_legacy(packed, s_pad=s_pad, k_pad=k_pad)
        b = LJ.make_segments(packed, s_pad=s_pad, k_pad=k_pad)
        _assert_stream_equal(a, b, (name, s_pad, k_pad))
    segs = LJ.make_segments(packed)
    want_s, want_p = LJ.remap_slots(segs)
    (got_s,), (got_p,) = LJ.remap_slots_batch([segs])
    _assert_stream_equal(want_s, got_s, name)
    assert want_p == got_p
    # PackPlan words: equal tables => equal plans => equal packed keys
    plan_a = LJ.make_pack_plan(16, packed.n_transitions, want_p or 1)
    plan_b = LJ.make_pack_plan(16, packed.n_transitions, got_p or 1)
    assert plan_a == plan_b


def test_remap_batch_heterogeneous_equals_per_history():
    """One batched call over MIXED families/shapes must reproduce the
    per-history remap exactly (the batch path pads to the widest
    stream; padding must never leak into allocations)."""
    streams = []
    for _, hist in FAMILIES:
        streams.append(LJ.make_segments(pack_history(hist)))
    want = [LJ.remap_slots(s) for s in streams]
    got_s, got_p = LJ.remap_slots_batch(streams)
    for (ws, wp), gs, gp, (name, _) in zip(want, got_s, got_p,
                                           FAMILIES):
        _assert_stream_equal(ws, gs, name)
        assert wp == gp, name


def test_stream_segments_legacy_flag_parity(monkeypatch):
    """The COMDB2_TPU_LEGACY_PACK=1 escape hatch routes the whole
    ingest through the per-op implementations — and produces the
    exact same streams and P_eff as the columnar default."""
    from comdb2_tpu.checker.batch import _stream_segments, pack_batch
    from comdb2_tpu.models.model import cas_register

    hists = [h for name, h in FAMILIES
             if name.startswith(("register", "cas", "crash"))]
    col_batch = pack_batch([list(h) for h in hists], cas_register())
    col_streams, col_p = _stream_segments(col_batch)

    monkeypatch.setenv("COMDB2_TPU_LEGACY_PACK", "1")
    leg_batch = pack_batch([list(h) for h in hists], cas_register())
    leg_streams, leg_p = _stream_segments(leg_batch)
    assert col_p == leg_p
    for i, (a, b) in enumerate(zip(leg_streams, col_streams)):
        _assert_stream_equal(a, b, i)


def test_error_class_parity():
    dbl = [O.invoke(0, "read", None), O.invoke(0, "write", 1)]
    with pytest.raises(RuntimeError):
        pack_history_columnar(dbl)
    with pytest.raises(RuntimeError):
        pack_history_legacy(dbl)
    orphan = [O.ok(0, "read", 1)]
    with pytest.raises(RuntimeError):
        pack_history_columnar(orphan)
    with pytest.raises(RuntimeError):
        pack_history_legacy(orphan)
    mismatch = [O.invoke(0, "write", 1), O.fail(0, "write", 2)]
    with pytest.raises(RuntimeError):
        pack_history_columnar(mismatch)
    with pytest.raises(RuntimeError):
        pack_history_legacy(mismatch)
    # completed=True keeps the pack loop's overwrite semantics
    bad = [op.with_(index=i) for i, op in enumerate(
        [O.invoke(0, "write", 1), O.invoke(0, "write", 2),
         O.ok(0, "write", 2)])]
    _assert_packed_equal(pack_history_legacy(bad, completed=True),
                         pack_history_columnar(bad, completed=True),
                         "double-pending")


def test_columnar_generator_roundtrip_and_validity():
    """The whole-batch generator's arrays must be exactly what the
    LEGACY packer produces from its own materialized ops (interning
    order, pairing, transitions), and every history must be
    linearizable under the host oracle."""
    from comdb2_tpu.checker import linear_host
    from comdb2_tpu.models.memo import memo
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops.synth_columnar import register_batch_packed

    ps = register_batch_packed(42, 12, 40, n_procs=4, values=3,
                               p_info=0.15)
    for i, p in enumerate(ps):
        _assert_packed_equal(pack_history_legacy(p.ops), p,
                             ("gen", i))
        r = linear_host.check(memo(cas_register(), p), p)
        assert r.valid is True, (i, r)


def test_check_batch_verdict_parity_legacy_vs_columnar(monkeypatch):
    """End-to-end: a mixed valid/invalid batch reaches identical
    (status, fail_at, n_final) through both ingest paths."""
    from comdb2_tpu.checker.batch import check_batch, pack_batch
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops.synth import mutate

    rng = random.Random(99)
    hs = []
    for i in range(6):
        h = register_history(rng, n_procs=3, n_events=40, values=3,
                             p_info=0.0)
        hs.append(mutate(rng, h) if i % 2 else h)
    col = check_batch(pack_batch([list(h) for h in hs],
                                 cas_register()), F=64, engine="keys")
    monkeypatch.setenv("COMDB2_TPU_LEGACY_PACK", "1")
    leg = check_batch(pack_batch([list(h) for h in hs],
                                 cas_register()), F=64, engine="keys")
    for a, b in zip(col, leg):
        assert np.array_equal(np.asarray(a), np.asarray(b))
