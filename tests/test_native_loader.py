"""Native EDN loader: parity with the Python reader + fallback."""

import pytest

from comdb2_tpu.ops import history as H
from comdb2_tpu.ops import native_loader as NL

DRIVER_EDN = """[
{:type :invoke :f :read :value nil :process 0 :time 10}
{:type :ok :f :read :value 3 :process 0 :uid 7 :time 20}
{:type :invoke :f :cas :value [2 4] :process 1 :time 30}
{:type :fail :f :cas :value [2 4] :process 1 :time 40}
{:type :invoke :f :write :value [1 [0 3]] :process 2 :time 50}
{:type :info :f :write :value [1 [0 3]] :process 2 :time 60}
{:type :invoke :f :add :value [5 nil] :process 3 :time 70}
]
"""

requires_native = pytest.mark.skipif(not NL.native_available(),
                                     reason="libct_sut.so not built")


@requires_native
def test_native_matches_python_reader():
    fast = NL.parse_history_fast(DRIVER_EDN)
    slow = H.parse_history(DRIVER_EDN)
    assert len(fast) == len(slow) == 7
    for a, b in zip(fast, slow):
        assert (a.process, a.type, a.f, a.value, a.time) == \
               (b.process, b.type, b.f, b.value, b.time)
    assert fast[4].value == (1, (0, 3))
    assert fast[6].value == (5, None)


@requires_native
def test_native_falls_back_outside_subset():
    # string values are valid EDN but outside the fast subset
    edn = '{:type :invoke :f :read :value "weird" :process 0 :time 1}'
    ops = NL.parse_history_fast(edn)
    assert len(ops) == 1
    assert ops[0].value == "weird"      # python reader handled it


@requires_native
def test_native_edge_values_match_python():
    """Shapes that once diverged: inner-vector-not-last, out-of-range
    ints, and INT64_MIN (the nil sentinel) must fall back, never skew."""
    cases = [
        "{:type :invoke :f :x :value [1 [2 3] 4] :process 0 :time 1}",
        "{:type :invoke :f :x :value 9223372036854775808 "
        ":process 0 :time 1}",
        "{:type :invoke :f :x :value -9223372036854775808 "
        ":process 0 :time 1}",
    ]
    for edn in cases:
        fast = NL.parse_history_fast(edn)
        slow = H.parse_history(edn)
        assert [(o.value,) for o in fast] == [(o.value,) for o in slow], edn


@requires_native
def test_native_rejects_malformed_gracefully():
    with pytest.raises(Exception):
        NL.parse_history_fast("{:type :invoke :f }")


@requires_native
def test_native_loader_on_driver_output(tmp_path):
    import os
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    binary = os.path.join(root, "native", "build", "ct_register")
    if not os.path.exists(binary):
        pytest.skip("native drivers not built")
    out = tmp_path / "h.edn"
    subprocess.run([binary, "-T", "3", "-i", "50", "-r", "30",
                    "-j", str(out), "-s", "2"], check=True,
                   capture_output=True)
    fast = NL.parse_history_fast(out.read_text())
    slow = H.parse_history(out.read_text())
    assert [(o.process, o.type, o.f, o.value) for o in fast] == \
           [(o.process, o.type, o.f, o.value) for o in slow]
