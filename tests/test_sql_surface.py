"""The SQL text surface (round-4 VERDICT Missing #1 / next-round #8).

The reference harness drives everything as SQL text parsed server-side
(session controls ``comdb2/core.clj:371-375``, statements dispatched at
``db/sqlinterfaces.c:5970``). sut_node now carries a per-connection SQL
front end (``native/src/sql_front.cpp``) translating the same statement
shapes into the typed verbs, plus a ``ct_sql`` mini-shell. These tests
prove (1) the statement grammar round-trips, (2) the register and G2
workloads PASS when driven purely as SQL text over the wire, and (3) a
negative control (``-T`` buggy-txn) is still DETECTED through the SQL
surface — i.e. the query-language path hides nothing.
"""

import os
import random
import socket
import subprocess

import pytest

from comdb2_tpu.checker import checkers as C
from comdb2_tpu.checker import independent as I
from comdb2_tpu.checker.workloads import g2_checker
from comdb2_tpu.harness import core, fake
from comdb2_tpu.harness import generator as G
from comdb2_tpu.models import model as M
from comdb2_tpu.ops.kv import tuple_
from comdb2_tpu.workloads import comdb2 as W
from comdb2_tpu.workloads.sql import (SqlClusterRegisterClient,
                                      SqlG2Client)
from comdb2_tpu.workloads.tcp import (ClusterControl, ClusterPartitioner,
                                      SutConnection, spawn_cluster)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BINARY = os.path.join(ROOT, "native", "build", "sut_node")
CT_SQL = os.path.join(ROOT, "native", "build", "ct_sql")

pytestmark = pytest.mark.skipif(not os.path.exists(BINARY),
                                reason="sut_node not built")


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _kill(procs):
    for p in procs:
        p.kill()
    for p in procs:
        p.wait()


def _conn(port, timeout=2.0):
    c = SutConnection("127.0.0.1", port, timeout_s=timeout)
    c.connect()
    return c


def test_sql_statement_grammar(tmp_path):
    """Every statement shape the reference tests issue, round-tripped
    through one node: session SETs, rowcount DML, the CAS-shaped
    guarded UPDATE, txns with predicate reads, set-table selects."""
    ports = _free_ports(1)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=800)
    try:
        c = _conn(ports[0])
        # session preamble (comdb2/core.clj:371-375)
        assert c.request("SET hasql ON") == "OK"
        assert c.request("set transaction serializable") == "OK"
        assert c.request("set max_retries 100000") == "OK"
        # single-statement DML classifies by rowcount
        assert c.request(
            "insert into register (id, val) values (1, 5)") == "ROWS 1"
        assert c.request(
            "select val from register where id = 1") == "V 5"
        assert c.request("select val from register where id = 9") == "NIL"
        # the CAS shape (comdb2/core.clj:432-474)
        assert c.request("update register set val = 7 "
                         "where id = 1 and val = 5") == "ROWS 1"
        assert c.request("update register set val = 9 "
                         "where id = 1 and val = 5") == "ROWS 0"
        assert c.request(
            "select val from register where id = 1") == "V 7"
        # txn: read + blind write + commit
        assert c.request("begin") == "OK"
        assert c.request("select val from register where id = 1") == "V 7"
        assert c.request(
            "update register set val = 3 where id = 1") == "ROWS 1"
        assert c.request("commit").startswith("OK")
        assert c.request(
            "select val from register where id = 1") == "V 3"
        # in-txn guarded update: predicate miss reports ROWS 0 and the
        # recorded read still validates at commit
        assert c.request("begin") == "OK"
        assert c.request("update register set val = 4 "
                         "where id = 1 and val = 99") == "ROWS 0"
        assert c.request("commit").startswith("OK")
        # set table (ctest/insert.c shapes)
        assert c.request(
            "insert into jepsen (value) values (42)") == "ROWS 1"
        assert c.request(
            "insert into jepsen (value) values (43)") == "ROWS 1"
        assert c.request("select value from jepsen") == "V 42 43"
        # G2 tables are txn-only
        assert c.request("select id, v from a where k = 2").startswith(
            "ERR")
        assert c.request("begin") == "OK"
        assert c.request("select id, v from a where k = 2") == "V"
        assert c.request("insert into a (id, k, v) values "
                         "(100, 2, 30)") == "ROWS 1"
        assert c.request("commit").startswith("OK")
        assert c.request("begin") == "OK"
        assert c.request(
            "select id, v from a where k = 2") == "V 100:30"
        assert c.request("rollback") == "OK"
        # cnonce replay: the same nonce re-executes as a replay, not a
        # second apply (blkseq dedup through the SQL surface)
        assert c.request("set cnonce 12345") == "OK"
        assert c.request(
            "insert into jepsen (value) values (77)") == "ROWS 1"
        assert c.request("set cnonce 12345") == "OK"
        assert c.request(
            "insert into jepsen (value) values (77)") == "ROWS 1"
        assert c.request("select value from jepsen") == "V 42 43 77"
        # garbage is rejected, not misparsed
        assert c.request("select val from nowhere").startswith("ERR")
        assert c.request("delete from register").startswith("ERR")
        # a WHERE clause the grammar can't express must ERR without
        # executing — an OR-connected guard must never demote the CAS
        # to a blind write (round-5 code review)
        assert c.request("select val from register "
                         "where id = 1") == "V 3"
        assert c.request("update register set val = 9 "
                         "where id = 1 or val = 3").startswith("ERR")
        assert c.request("update register set val = 9 "
                         "where id = 1 and garbage").startswith("ERR")
        assert c.request("select val from register "
                         "where id = 1 or id = 2").startswith("ERR")
        assert c.request("select val from register "
                         "where id = 1") == "V 3"     # value untouched
        # known statements with parsed tails still work
        assert c.request("select value from jepsen "
                         "order by value") == "V 42 43 77"
        # isolation levels come from a known vocabulary: a typo must
        # ERR, never silently run at the wrong isolation
        assert c.request("set transaction read committed") == "OK"
        assert c.request("set transaction serialzable").startswith(
            "ERR")
        assert c.request("set transaction serializable") == "OK"
        c.close()
    finally:
        _kill(procs)


def test_ct_sql_shell():
    """The ct_sql mini-shell (the cdb2sql role) end to end: session
    setup, DML, select — and exit status 1 on an ERR reply."""
    if not os.path.exists(CT_SQL):
        pytest.skip("ct_sql not built")
    ports = _free_ports(1)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=800)
    try:
        target = f"127.0.0.1:{ports[0]}"
        out = subprocess.run(
            [CT_SQL, target,
             "-c", "set hasql on",
             "-c", "insert into register (id, val) values (3, 8)",
             "-c", "select val from register where id = 3"],
            capture_output=True, text=True, timeout=10)
        assert out.returncode == 0, out
        assert out.stdout.splitlines() == ["OK", "ROWS 1", "V 8"]
        bad = subprocess.run(
            [CT_SQL, target, "-c", "select nonsense"],
            capture_output=True, text=True, timeout=10)
        assert bad.returncode == 1
        assert bad.stdout.startswith("ERR")
    finally:
        _kill(procs)


N_KEYS = 4


def _keyed_gen(seed):
    rngs = {}

    def op(test=None, process=None):
        rng = rngs.get(process)
        if rng is None:
            rng = rngs[process] = random.Random(f"{seed}/{process}")
        k = rng.randrange(N_KEYS)
        f = rng.choice(["read", "write", "cas", "cas"])
        if f == "read":
            return {"type": "invoke", "f": "read",
                    "value": tuple_(k, None)}
        if f == "write":
            return {"type": "invoke", "f": "write",
                    "value": tuple_(k, rng.randrange(5))}
        return {"type": "invoke", "f": "cas",
                "value": tuple_(k, (rng.randrange(5),
                                    rng.randrange(5)))}
    return op


def test_sql_register_workload_valid(tmp_path):
    """The flagship register workload driven ENTIRELY as SQL text over
    a 3-node cluster (with a partition window) stays linearizable —
    the reference's register-tester shape (comdb2/core.clj:567-613)
    through the query-language surface."""
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=300,
                          elect_ms=500, lease_ms=300)
    try:
        ctl = ClusterControl(ports)
        t = fake.noop_test()
        t.update({
            "nodes": [], "concurrency": 5, "name": "sql-register",
            "store-root": str(tmp_path / "store"),
            "client": SqlClusterRegisterClient(ports, timeout_s=0.45),
            "model": M.cas_register(),
            "nemesis": ClusterPartitioner(ctl, isolate_primary=True),
            "generator": G.nemesis(
                G.seq([G.sleep(0.3), {"type": "info", "f": "start"},
                       G.sleep(1.0), {"type": "info", "f": "stop"}]),
                G.time_limit(3.0, G.stagger(0.01, _keyed_gen(5)))),
            "checker": I.checker(C.Linearizable(backend="host")),
        })
        result = core.run(t)
        ctl.heal()
        assert result["results"]["valid?"] is True, result["results"]
        oks = [op for op in result["history"] if op.type == "ok"]
        assert len(oks) >= 40, len(oks)
    finally:
        _kill(procs)


def test_sql_g2_workload_valid(tmp_path):
    """G2 driven as SQL text: predicate SELECTs + guarded INSERT in
    BEGIN..COMMIT; at most one insert commits per key."""
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=500)
    try:
        t = fake.noop_test()
        t.update({
            "nodes": [], "concurrency": 6, "name": "sql-g2",
            "store-root": str(tmp_path / "store"),
            "client": SqlG2Client(ports, timeout_s=0.6),
            "model": None,
            "generator": G.clients(G.time_limit(3.0, W.g2_gen())),
            "checker": g2_checker,
        })
        result = core.run(t)
        res = result["results"]
        assert res["valid?"] is True, res
        assert res["legal-count"] >= 5, res
    finally:
        _kill(procs)


def test_sql_g2_buggy_txn_control_detected(tmp_path):
    """Negative control through the SQL surface: with ``-T`` the
    server commits without OCC validation, so two SQL txns that both
    predicate-read-empty can both insert — the G2 anomaly must be
    flagged even when driven as SQL text."""
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=800,
                          flags=["-T"])
    try:
        # deterministic interleaving: two sessions, same key — both
        # begin, both predicate-read empty, both insert, both commit
        c1, c2 = _conn(ports[0]), _conn(ports[1])
        for c in (c1, c2):
            assert c.request("set hasql on") == "OK"
            assert c.request("begin") == "OK"
            assert c.request("select id, v from a where k = 7") == "V"
            assert c.request("select id, v from b where k = 7") == "V"
        assert c1.request(
            "insert into a (id, k, v) values (1, 7, 30)") == "ROWS 1"
        assert c2.request(
            "insert into b (id, k, v) values (2, 7, 30)") == "ROWS 1"
        r1, r2 = c1.request("commit"), c2.request("commit")
        assert r1.startswith("OK") and r2.startswith("OK"), (r1, r2)

        # both committed = the anomaly; the checker must flag it
        from comdb2_tpu.ops.op import Op
        h = [Op(process=0, type="ok", f="insert",
                value=tuple_(7, (1, None))),
             Op(process=1, type="ok", f="insert",
                value=tuple_(7, (None, 2)))]
        res = g2_checker.check({}, None, h, {})
        assert res["valid?"] is False, res
        c1.close()
        c2.close()
    finally:
        _kill(procs)
