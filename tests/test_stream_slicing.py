"""Multi-device stream slicing as pure functions (round-2 Weak #2).

The streamed kernel's slice assignment, per-slice verdict merge, and
UNKNOWN-escalation previously ran with more than one device exactly
nowhere: the kernel doesn't lower on CPU, the multichip dryrun
validates only the keys-sharded XLA path, and the real bench has one
chip. The logic now lives in pure functions
(``pallas_seg.plan_stream_slices`` / ``merge_stream_slice``,
``batch.escalation_indices`` / ``merge_escalation``) exercised here on
CPU with fake device lists and fake result buffers — plus the full
escalation WIRING in ``check_batch`` driven through a faked stream
engine, with the escalated history resolved by the real XLA ladder."""

import numpy as np
import pytest

from comdb2_tpu.checker import batch as B
from comdb2_tpu.checker import linear_jax as LJ
from comdb2_tpu.checker import pallas_seg as PSEG


# --- plan_stream_slices ------------------------------------------------


def test_slices_cover_batch_in_order_no_devices():
    plan = PSEG.plan_stream_slices(10, 0, max_stream_b=4)
    assert plan == [(0, 4, 0), (4, 8, 0), (8, 10, 0)]


def test_slices_spread_across_devices_round_robin():
    # 17 histories over 8 fake devices: group = ceil(17/8) = 3
    plan = PSEG.plan_stream_slices(17, 8, max_stream_b=64)
    assert [s[:2] for s in plan] == [(0, 3), (3, 6), (6, 9), (9, 12),
                                     (12, 15), (15, 17)]
    assert [s[2] for s in plan] == [0, 1, 2, 3, 4, 5]
    # every history appears exactly once, in order
    covered = [i for s, e, _ in plan for i in range(s, e)]
    assert covered == list(range(17))


def test_slices_respect_vmem_cap_even_with_devices():
    # huge batch over 2 devices: slices never exceed the VMEM cap and
    # wrap around the devices
    plan = PSEG.plan_stream_slices(100, 2, max_stream_b=16)
    assert all(e - s <= 16 for s, e, _ in plan)
    assert [d for _, _, d in plan] == [0, 1, 0, 1, 0, 1, 0]
    covered = [i for s, e, _ in plan for i in range(s, e)]
    assert covered == list(range(100))


def test_slices_default_cap_is_kernel_bound():
    plan = PSEG.plan_stream_slices(PSEG.MAX_STREAM_B * 2 + 1, 0)
    assert all(e - s <= PSEG.MAX_STREAM_B for s, e, _ in plan)


def test_single_device_list_still_slices_whole_batch():
    # devices=[one device] (the mesh-of-1 case): same coverage
    plan = PSEG.plan_stream_slices(5, 1, max_stream_b=4)
    covered = [i for s, e, _ in plan for i in range(s, e)]
    assert covered == list(range(5))
    assert all(d == 0 for _, _, d in plan)


# --- merge_stream_slice ------------------------------------------------


def test_merge_converts_global_fail_segments_to_local():
    # the kernel reports fail segments in slice-global coordinates;
    # history 1 starts at segment 7 and failed at global segment 9
    res = np.array([[0, -1, 3],       # valid, 3 final configs
                    [1, 9, 0],        # invalid at global seg 9
                    [2, -1, 0]],      # unknown (overflow)
                   np.int32)
    starts = np.array([0, 7, 12], np.int64)
    out = PSEG.merge_stream_slice(res, starts, 3)
    assert out == [(0, -1, 3), (1, 2, 0), (2, -1, 0)]


def test_merge_handles_partial_slice():
    # the results buffer is padded; only the first n rows are real
    res = np.array([[0, -1, 1], [0, -1, 2], [99, 99, 99]], np.int32)
    starts = np.array([0, 4, 0], np.int64)
    assert PSEG.merge_stream_slice(res, starts, 2) == [(0, -1, 1),
                                                       (0, -1, 2)]


def test_plan_plus_merge_reassembles_solo_order():
    """The invariant the multi-device path must keep: slicing a batch
    over N fake devices and concatenating per-slice merges yields
    exactly the solo-path verdict list."""
    rng = np.random.default_rng(0)
    B_n = 23
    solo = [(int(rng.integers(0, 3)), int(rng.integers(-1, 5)),
             int(rng.integers(0, 9))) for _ in range(B_n)]
    for n_dev in (0, 1, 3, 8):
        plan = PSEG.plan_stream_slices(B_n, n_dev, max_stream_b=4)
        merged = []
        for s, e, _ in plan:
            # fake the kernel's result buffer for this slice: global
            # fail coords = local + a fake per-history segment start
            starts = np.arange(e - s, dtype=np.int64) * 10
            res = np.zeros((e - s, 3), np.int32)
            for i, b in enumerate(range(s, e)):
                st, fl, nf = solo[b]
                res[i] = (st, fl + starts[i] if fl >= 0 else -1, nf)
            merged.extend(PSEG.merge_stream_slice(res, starts, e - s))
        assert merged == solo, f"n_dev={n_dev}"


# --- escalation --------------------------------------------------------


def test_escalation_only_when_budget_exceeds_kernel():
    status = np.array([0, 2, 1, 2], np.int32)
    assert B.escalation_indices(status, F=128, kernel_f=128).size == 0
    idx = B.escalation_indices(status, F=1024, kernel_f=128)
    assert idx.tolist() == [1, 3]


def test_merge_escalation_folds_subbatch_back():
    status = np.array([0, 2, 1, 2], np.int32)
    fail_at = np.array([-1, -1, 5, -1], np.int64)
    n_final = np.array([3, 0, 0, 0], np.int32)
    idx = np.array([1, 3])
    st, fa, nf = B.merge_escalation(
        status, fail_at, n_final, idx,
        np.array([0, 1], np.int32), np.array([-1, 9], np.int64),
        np.array([7, 0], np.int32))
    assert st.tolist() == [0, 0, 1, 1]
    assert fa.tolist() == [-1, -1, 5, 9]
    assert nf.tolist() == [3, 7, 0, 0]
    # inputs are not mutated (pure)
    assert status.tolist() == [0, 2, 1, 2]


def test_f_escalation_wiring_with_fake_stream_engine(monkeypatch):
    """The escalation WIRING in check_batch's stream path, exercised
    on CPU by faking the per-slice dispatch (the real kernel doesn't
    lower here): the fake reports UNKNOWN for one history, and
    check_batch must route exactly that history through the real XLA
    engines at the caller's F and fold the resolved verdict back —
    final results equal solo."""
    import random

    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops.synth import register_history

    rng = random.Random(3)
    hs = [register_history(rng, n_procs=3, n_events=40, values=3,
                           p_info=0.0) for _ in range(4)]
    solo = [B.check_batch(B.pack_batch([h], cas_register()), F=1024)
            for h in hs]
    assert all(int(s[0][0]) == 0 for s in solo)   # all genuinely valid

    batch = B.pack_batch(hs, cas_register())

    def fake_dispatch(succ, segs_list, spec, n_states, n_transitions,
                      device=None):
        # history 2 "overflows the kernel frontier"; others check out
        # (4 histories = one pipeline slice, so slice-local indices
        # are batch indices)
        res = np.zeros((len(segs_list), 3), np.int32)
        for i in range(len(segs_list)):
            if i == 2:
                res[i] = (LJ.UNKNOWN, -1, 0)
            else:
                res[i] = (int(solo[i][0][0]), -1, int(solo[i][2][0]))
        return res, np.zeros(len(segs_list), np.int64)

    monkeypatch.setattr(PSEG, "available", lambda: True)
    monkeypatch.setattr(PSEG, "stream_dispatch", fake_dispatch)

    info: dict = {}
    status, fail_at, n_final = B.check_batch(batch, F=1024,
                                             engine="stream", info=info)
    # the UNKNOWN resolved through the ladder; everything matches solo
    for b in range(len(hs)):
        assert int(status[b]) == int(solo[b][0][0]), (b, status)
    assert info.get("escalated", {}).get("count") == 1, info
    assert info["escalated"]["engine"] in ("keys", "flat", "vmap")

    # at F == kernel budget there is nothing to escalate: the UNKNOWN
    # must surface as-is (re-running at the same budget could only
    # reproduce the overflow)
    info2: dict = {}
    status2, _, _ = B.check_batch(batch, F=PSEG.F, engine="stream",
                                  info=info2)
    assert int(status2[2]) == LJ.UNKNOWN
    assert "escalated" not in info2
