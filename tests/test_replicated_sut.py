"""Replicated in-tree SUT: leader election + durable-LSN majority acks
over TCP, exercised by the register workload + partition nemesis.

Round-2 VERDICT Missing #1: the old static-primary cluster just stalled
under a master partition. Now a partition that cuts off the primary
forces a real ELECTION (term votes gated on log up-to-dateness, the
bdb/rep.c:408-520 role): writes re-route through the new leader inside
the fault window and the history stays linearizable, while the
``--split-brain`` control (a quorum-less leader that neither demotes
nor waits for majority acks) produces real divergent writes/reads the
checker must flag INVALID. All generators are seeded with per-process
derived rngs — a failing run prints its seed, and each worker's op
stream replays exactly (scheduling still decides how many ops each
worker gets to run; round-2 Weak #4)."""

import os
import random
import socket
import time

import pytest

from comdb2_tpu.checker import checkers as C
from comdb2_tpu.checker import independent as I
from comdb2_tpu.harness import core, fake
from comdb2_tpu.harness import generator as G
from comdb2_tpu.models import model as M
from comdb2_tpu.ops.kv import tuple_
from comdb2_tpu.workloads import comdb2 as W
from comdb2_tpu.workloads.tcp import (ClusterControl, ClusterPartitioner,
                                      TcpClusterRegisterClient,
                                      spawn_cluster)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BINARY = os.path.join(ROOT, "native", "build", "sut_node")

pytestmark = pytest.mark.skipif(not os.path.exists(BINARY),
                                reason="sut_node not built")


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _cluster_test(tmp_path, ports, name, **kw):
    t = fake.noop_test()
    t.update({
        "nodes": [], "concurrency": 5, "name": name,
        "store-root": str(tmp_path / "store"),
        "client": TcpClusterRegisterClient(ports, timeout_s=0.45),
        "model": M.cas_register(),
        "generator": G.clients(G.limit(120, G.mix([W.r, W.w, W.cas]))),
        "checker": I.checker(C.Linearizable(backend="host")),
    })
    t.update(kw)
    return t


def _kill(procs):
    for p in procs:
        p.kill()
    for p in procs:
        p.wait()


def test_cluster_discovery_and_replication():
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=800)
    try:
        ctl = ClusterControl(ports)
        info = ctl.info()
        assert [n["role"] for n in info] == ["primary", "replica",
                                             "replica"]
        assert ctl.primary() == 0
    finally:
        _kill(procs)


def test_durable_cluster_valid_without_faults(tmp_path):
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=800)
    try:
        t = _cluster_test(tmp_path, ports, "cluster-register")
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
        oks = [op for op in result["history"] if op.type == "ok"]
        assert len(oks) >= 60
    finally:
        _kill(procs)


N_KEYS = 8


def _keyed(f, seed):
    """Spread ops over N_KEYS independent registers (the reference's
    register test is keyed the same way): every write that times out in
    a partition window stays pending forever, and the checker's config
    set is exponential in pending ops PER KEY — keying is what keeps
    fault-heavy histories verifiable (independent.clj:252-300).

    Each PROCESS draws from its own rng derived from (seed, process, f)
    — workers run on concurrent threads, so a shared rng's draw order
    would be scheduler-dependent and the seed would not replay."""
    rngs = {}

    def op(test=None, process=None):
        rng = rngs.get(process)
        if rng is None:
            rng = rngs[process] = random.Random(f"{seed}/{process}/{f}")
        k = rng.randrange(N_KEYS)
        if f == "read":
            return {"type": "invoke", "f": "read",
                    "value": tuple_(k, None)}
        if f == "write":
            return {"type": "invoke", "f": "write",
                    "value": tuple_(k, rng.randrange(5))}
        return {"type": "invoke", "f": "cas",
                "value": tuple_(k, (rng.randrange(5),
                                    rng.randrange(5)))}
    return op


def _nemesis_gen(seed, secs=4.0, window=1.0, lead=0.3, gap=0.6,
                 cycles=2, mix=None):
    """Clients run for the whole span (time-limited, not op-limited: an
    op-count budget can drain before the first partition opens) while
    the nemesis cycles ``cycles`` partition windows of ``window``
    seconds."""
    kr, kw, kc = (_keyed("read", seed), _keyed("write", seed),
                  _keyed("cas", seed))
    steps = [G.sleep(lead)]
    for _ in range(cycles):
        steps += [{"type": "info", "f": "start"}, G.sleep(window),
                  {"type": "info", "f": "stop"}, G.sleep(gap)]
    return G.nemesis(
        G.seq(steps),
        G.time_limit(secs, G.stagger(
            0.01, G.mix(mix or [kr, kr, kw, kc]))))


def test_durable_cluster_valid_under_partition(tmp_path):
    """Master-targeted partitions against the durable cluster: writes
    that can't reach a majority time out into info ops; the history
    stays linearizable (seed 11)."""
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=300)
    try:
        ctl = ClusterControl(ports)
        t = _cluster_test(
            tmp_path, ports, "cluster-nemesis-durable",
            nemesis=ClusterPartitioner(ctl, isolate_primary=True),
            generator=_nemesis_gen(seed=11))
        result = core.run(t)
        ctl.heal()
        assert result["results"]["valid?"] is True, \
            ("seed 11", result["results"])
        infos = [op for op in result["history"]
                 if op.type == "info" and op.process != "nemesis"]
        assert infos, "partition should have produced indeterminate ops"
    finally:
        _kill(procs)


def test_partition_forces_election_and_demotion():
    """Cutting the primary off elects a new leader on the majority side
    (term bump, log-up-to-date vote gating) while the old primary
    demotes on lease loss and refuses to serve its stale state."""
    from comdb2_tpu.workloads.tcp import SutConnection

    def req(port, line, timeout=1.5):
        conn = SutConnection("127.0.0.1", port, timeout_s=timeout)
        try:
            conn.connect()
            return conn.request(line)
        except TimeoutError:
            return "TIMEOUT"
        finally:
            conn.close()

    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=400,
                          elect_ms=500, lease_ms=300)
    try:
        ctl = ClusterControl(ports)
        assert req(ports[1], "W 1 42").startswith("OK")
        ctl.partition([0], [1, 2])
        deadline = time.monotonic() + 6.0
        new_leader = None
        while time.monotonic() < deadline and new_leader is None:
            for n in ctl.info():
                if n["role"] == "primary" and n["node"] != 0:
                    new_leader = n
            time.sleep(0.05)
        assert new_leader is not None, "no election happened"
        assert new_leader["term"] > 1
        # writes flow through the new leader (forwarded by replicas)
        assert req(ports[new_leader["node"]], "W 1 77").startswith("OK")
        # the deposed primary must NOT serve its stale register
        assert req(ports[0], "R 1", timeout=1.2) in ("UNKNOWN", "TIMEOUT")
        ctl.heal()
        assert ctl.await_replicated(timeout_s=8.0)
        assert req(ports[0], "R 1") == "V 77"
    finally:
        _kill(procs)


def test_durable_cluster_valid_through_failover(tmp_path):
    """The flagship failover run: a partition window long enough for an
    election (window 2s > node-1 election timeout 650ms) must re-route
    writes to the new leader INSIDE the window, and the whole history —
    spanning two leaderships — stays linearizable (seed 23)."""
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=300,
                          elect_ms=500, lease_ms=300)
    try:
        ctl = ClusterControl(ports)
        t = _cluster_test(
            tmp_path, ports, "cluster-failover",
            nemesis=ClusterPartitioner(ctl, isolate_primary=True),
            generator=_nemesis_gen(seed=23, secs=6.0, window=2.0,
                                   lead=0.4, gap=0.8))
        result = core.run(t)
        terms = [n.get("term", 1) for n in ctl.info()
                 if n["role"] != "down"]
        ctl.heal()
        assert result["results"]["valid?"] is True, \
            ("seed 23", result["results"])
        assert max(terms) > 1, "partition never forced an election"

        # ok-completed WRITES inside a partition window prove re-routing:
        # the isolated old primary cannot reach a majority, so only a
        # freshly elected leader can have acked them
        h = result["history"]
        starts = [op.time for op in h
                  if op.process == "nemesis" and op.f == "start"
                  and op.type == "info" and op.value is not None]
        stops = [op.time for op in h
                 if op.process == "nemesis" and op.f == "stop"
                 and op.type == "info" and op.value is None]
        assert starts, "nemesis never fired"
        pairs = {}          # invoke time per (process, f) in flight
        rerouted = 0
        for op in h:
            if op.process == "nemesis" or op.f not in ("write", "cas"):
                continue
            if op.type == "invoke":
                pairs[op.process] = op.time
            elif op.type == "ok":
                t0 = pairs.get(op.process)
                if t0 is None:
                    continue
                for s in starts:
                    stop = min((e for e in stops if e > s),
                               default=None)
                    # 1s margin past the cut: election + old in-flights
                    if stop and t0 > s + 1.0e9 and op.time < stop:
                        rerouted += 1
        assert rerouted > 0, \
            "no write completed ok inside a partition window"
    finally:
        _kill(procs)


def test_no_durable_partition_detected_invalid(tmp_path):
    """Negative control #1: same workload, same partitions, but the
    cluster acknowledges writes before replication (--no-durable) — a
    partitioned replica serves stale reads and the checker must flag
    the history invalid. Detection depends on which worker reads from
    which node during a window, so retry a few seeded rounds."""
    seeds = [31, 32, 33, 34]
    for seed in seeds:
        ports = _free_ports(3)
        procs = spawn_cluster(BINARY, ports, durable=False)
        try:
            ctl = ClusterControl(ports)
            t = _cluster_test(
                tmp_path, ports, f"cluster-nodurable-{seed}",
                nemesis=ClusterPartitioner(ctl, isolate_primary=True),
                generator=_nemesis_gen(seed=seed))
            result = core.run(t)
            ctl.heal()
            if result["results"]["valid?"] is False:
                return
        finally:
            _kill(procs)
    raise AssertionError(
        f"no-durable cluster never produced a detectable stale "
        f"read/lost write under partitions (seeds {seeds})")


def test_split_brain_control_detected_invalid(tmp_path):
    """Negative control #2 (the election-era control): with -B a leader
    that loses quorum neither demotes nor waits for majority acks, so
    after the majority side elects, BOTH primaries accept writes and
    serve reads — divergent register states the linearizable checker
    must catch. Retry a few seeded rounds (whether a worker's reads
    straddle both sides inside a window is timing-dependent)."""
    seeds = [41, 42, 43, 44]
    for seed in seeds:
        ports = _free_ports(3)
        procs = spawn_cluster(BINARY, ports, durable=True,
                              timeout_ms=300, elect_ms=500,
                              lease_ms=300, flags=["-B"])
        try:
            ctl = ClusterControl(ports)
            t = _cluster_test(
                tmp_path, ports, f"cluster-splitbrain-{seed}",
                nemesis=ClusterPartitioner(ctl, isolate_primary=True),
                generator=_nemesis_gen(seed=seed, secs=6.0, window=2.0,
                                       lead=0.4, gap=0.8))
            result = core.run(t)
            ctl.heal()
            if result["results"]["valid?"] is False:
                return
        finally:
            _kill(procs)
    raise AssertionError(
        f"split-brain control never produced a detectable divergence "
        f"(seeds {seeds})")


def test_replication_protocol_certifies_before_counting():
    """Protocol-level pin of the repair path: acks carry the CERTIFIED
    prefix (verified to match the current leader's log), never raw
    applied — a rejoined node's divergent suffix must not count toward
    durability, and the low ack is what drives suffix repair. The test
    plays two successive leaders against one node over raw TCP."""
    from comdb2_tpu.workloads.tcp import SutConnection

    import subprocess

    from comdb2_tpu.workloads.tcp import _wait_ready

    ports = _free_ports(3)
    # only node 1 is real (peers 0/2 never answer); elect_ms is huge so
    # it never campaigns and our scripted leaders fully own its state
    proc = subprocess.Popen(
        [BINARY, "-i", "1", "-n", ",".join(map(str, ports)),
         "-t", "300", "-e", "60000", "-l", "300"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        _wait_ready(proc, ports[1], time.monotonic() + 5.0, "sut_node")
    except RuntimeError:
        proc.kill()
        proc.wait()
        raise
    conn = SutConnection("127.0.0.1", ports[1], timeout_s=1.0)
    conn.connect()
    try:
        # leader 0, term 5: heartbeat certifies nothing yet
        assert conn.request("H 0 5 0") == "A 0"
        # replicate entry 1 (term 5): append + certify
        assert conn.request("E 0 5 1 5 0 W 1 7 0 0 0") == "A 1"
        # duplicate with matching term: still certified at 1
        assert conn.request("E 0 5 1 5 0 W 1 7 0 0 0") == "A 1"
        # leader 2 takes over in term 7: certification RESETS to the
        # committed prefix (0) even though applied is still 1 — the
        # old ack value must not leak into the new leader's counts
        assert conn.request("H 2 7 0") == "A 0"
        # the new leader's entry 1 conflicts (term 7 vs 5): the node
        # truncates its divergent suffix, appends, re-certifies
        assert conn.request("E 2 7 1 7 0 W 1 9 0 0 0") == "A 1"
        # commit it via the piggybacked durable lsn, then verify the
        # committed register state took the REPAIRED value
        assert conn.request("H 2 7 1") == "A 1"
        info = conn.request("I").split()
        assert info[2] == "replica" and int(info[3]) == 1
        # a durable-mode replica serves NO local state — register and
        # set reads both route to the leader (here unreachable, so
        # they come back indeterminate after the hang); the repaired
        # log itself is pinned by the A/I assertions above
        assert conn.request("S") == "UNKNOWN"
    finally:
        conn.close()
        proc.kill()
        proc.wait()


def test_dedup_replays_recorded_outcome():
    """Protocol pin of the blkseq role: a nonce-wrapped mutation that
    already applied returns its RECORDED outcome on retry — the cas
    does not re-execute (which would FAIL its precondition the second
    time), and the register shows exactly one application."""
    from comdb2_tpu.workloads.tcp import SutConnection

    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=800)
    conn = SutConnection("127.0.0.1", ports[0], timeout_s=2.0)
    try:
        conn.connect()
        r1 = conn.request("M 901 W 1 5")
        assert r1.startswith("OK")
        # replay of the applied write: same recorded lsn
        assert conn.request("M 901 W 1 5") == r1
        r2 = conn.request("M 902 C 1 5 6")
        assert r2.startswith("OK")
        # the replayed cas must NOT re-execute (regs is now 6 != 5,
        # re-execution would FAIL); dedup returns the recorded OK
        assert conn.request("M 902 C 1 5 6") == r2
        assert conn.request("R 1") == "V 6"
        # a FAILed cas is never logged: its retry re-executes fresh
        assert conn.request("M 903 C 1 99 7") == "FAIL"
        assert conn.request("M 903 C 1 6 7").startswith("OK")
        assert conn.request("R 1") == "V 7"
    finally:
        conn.close()
        _kill(procs)


def test_no_dedup_retried_cas_double_applies():
    """The -D negative control at the protocol level: without the
    dedup table a replayed cas re-executes — the retry FAILs its
    precondition even though the first attempt applied, the
    fail-but-applied outcome the checker must treat as an anomaly."""
    from comdb2_tpu.workloads.tcp import SutConnection

    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=800,
                          flags=["-D"])
    conn = SutConnection("127.0.0.1", ports[0], timeout_s=2.0)
    try:
        conn.connect()
        assert conn.request("M 901 W 1 5").startswith("OK")
        assert conn.request("M 902 C 1 5 6").startswith("OK")
        # the "retry": re-executes and fails — but the first DID apply
        assert conn.request("M 902 C 1 5 6") == "FAIL"
        assert conn.request("R 1") == "V 6"
    finally:
        conn.close()
        _kill(procs)


def test_ha_driver_few_infos_under_partitions(tmp_path):
    """VERDICT #4's done-criterion: ct_register over a partitioned
    cluster produces MOSTLY ok/fail (the nonce retry resolves fault-
    window ops) and the history stays linearizable. Before dedup every
    possibly-delivered op was an instant info and fault histories
    drowned in forever-pending ops."""
    import subprocess
    import threading

    from comdb2_tpu.checker import analysis
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops.history import parse_history

    ports = _free_ports(3)
    nodes = ",".join(f"127.0.0.1:{p}" for p in ports)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=400,
                          elect_ms=500, lease_ms=300)
    ctl = ClusterControl(ports)
    stop = threading.Event()

    def nemesis():
        while not stop.wait(0.8):
            pri = ctl.primary()
            if pri is None:
                continue
            ctl.partition([pri], [i for i in range(3) if i != pri])
            if stop.wait(1.2):
                break
            ctl.heal()

    th = threading.Thread(target=nemesis)
    th.start()
    out = tmp_path / "ha_dedup.edn"
    try:
        p = subprocess.run(
            [os.path.join(ROOT, "native", "build", "ct_register"),
             "-T", "4", "-r", "8", "-d", nodes, "-j", str(out),
             "-s", "77"],
            capture_output=True, text=True, timeout=120)
        assert p.returncode == 0, p.stderr
    finally:
        stop.set()
        th.join()
        ctl.heal()
        _kill(procs)

    h = parse_history(out.read_text())
    counts = {}
    for op in h:
        counts[op.type] = counts.get(op.type, 0) + 1
    n_ops = counts.get("invoke", 0)
    n_info = counts.get("info", 0)
    assert n_ops >= 200, counts
    # "mostly ok/fail, few info": the retry budget resolves all but
    # the ops still in flight when a window outlives the budget
    assert n_info <= max(10, n_ops // 20), counts
    a = analysis(cas_register(), h, backend="host")
    assert a.valid is True, "seed 77 HA history must be linearizable"


def test_no_dedup_cluster_detected_invalid():
    """The -D control, end to end and DETERMINISTIC: drive the exact
    dangerous interleaving over the wire — first attempt delivered to
    the leader during a partition blip (durable wait times out
    UNKNOWN), entry commits after heal, retry re-executes and FAILs
    its precondition — then check the client-visible history. With
    dedup the same interleaving replays the recorded OK and the
    history is linearizable; without it the cas is recorded ``fail``
    though it applied, and the committed read of its value has no
    explanation: the checker must flag INVALID."""
    from comdb2_tpu.checker import analysis
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops.op import Op
    from comdb2_tpu.workloads.tcp import SutConnection

    def run_once(no_dedup):
        ports = _free_ports(3)
        flags = ["-D"] if no_dedup else []
        procs = spawn_cluster(BINARY, ports, durable=True,
                              timeout_ms=300, elect_ms=3000,
                              lease_ms=300, flags=flags)
        ctl = ClusterControl(ports)
        conn = SutConnection("127.0.0.1", ports[0], timeout_s=2.0)
        try:
            conn.connect()
            assert conn.request("W 1 5").startswith("OK")
            # blip: leader cut from both replicas, shorter than any
            # election timeout — leadership never moves
            ctl.partition([0], [1, 2])
            r1 = conn.request("M 77 C 1 5 6")
            assert r1 == "UNKNOWN", r1   # delivered, durable wait out
            ctl.heal()
            assert ctl.await_replicated(timeout_s=8.0)
            r2 = conn.request("M 77 C 1 5 6")    # the HA retry
            r3 = conn.request("R 1")
            return r2, r3
        finally:
            conn.close()
            ctl.heal()
            _kill(procs)

    def verdict(cas_outcome, read_reply):
        # the client-visible history: write ok, one cas with the
        # retry's final outcome, one committed read
        val = (None if read_reply == "NIL"
               else int(read_reply.split()[1]))
        h = [Op(process=0, type="invoke", f="write", value=5, time=0),
             Op(process=0, type="ok", f="write", value=5, time=1),
             Op(process=1, type="invoke", f="cas", value=(5, 6), time=2),
             Op(process=1, type=cas_outcome, f="cas", value=(5, 6),
                time=3),
             Op(process=2, type="invoke", f="read", value=None, time=4),
             Op(process=2, type="ok", f="read", value=val, time=5)]
        return analysis(cas_register(), h, backend="host").valid

    # dedup ON: the retry replays the recorded OK — linearizable
    r2, r3 = run_once(no_dedup=False)
    assert r2.startswith("OK"), r2
    assert r3 == "V 6", r3
    assert verdict("ok", r3) is True

    # dedup OFF: the retry re-executes and FAILs though the first
    # attempt committed — the history must be INVALID
    r2, r3 = run_once(no_dedup=True)
    assert r2 == "FAIL", r2
    assert r3 == "V 6", r3
    assert verdict("fail", r3) is False


def test_clock_scrambler_harmless_against_monotonic_leases(tmp_path):
    """Clock faults now target a real time-dependent mechanism (the
    serving lease). The CORRECT implementation measures leases with
    monotonic deltas, so scrambling every node's wall clock — combined
    with partitions — must not produce an anomaly (seed 61)."""
    from comdb2_tpu.workloads.tcp import ClusterClockScrambler

    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=300,
                          elect_ms=500, lease_ms=300)
    try:
        ctl = ClusterControl(ports)
        part = ClusterPartitioner(ctl, isolate_primary=True)
        clock = ClusterClockScrambler(ctl, rng=random.Random(61))

        class Both:
            """partition + clock scrambling in the same windows"""

            def setup(self, test, node):
                return self

            def teardown(self, test):
                part.teardown(test)
                clock.teardown(test)

            def invoke(self, test, op):
                clock.invoke(test, op)
                return part.invoke(test, op)

        t = _cluster_test(
            tmp_path, ports, "cluster-clock-scramble",
            nemesis=Both(),
            generator=_nemesis_gen(seed=61, secs=6.0, window=1.5,
                                   lead=0.4, gap=0.7))
        result = core.run(t)
        ctl.clocks_reset()
        ctl.heal()
        assert result["results"]["valid?"] is True, \
            ("seed 61", result["results"])
    finally:
        _kill(procs)


def test_bad_lease_clock_fault_serves_stale_read():
    """The -L control, DETERMINISTIC: a backward clock jump on a
    partitioned leader stretches its dead lease (elapsed time goes
    negative), so it keeps serving its committed-but-now-stale
    register after the majority elects a new leader and commits a new
    value — the stale-lease read the checker must flag. The same
    sequence against the correct (monotonic) cluster yields UNKNOWN
    from the deposed leader instead."""
    from comdb2_tpu.checker import analysis
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops.op import Op
    from comdb2_tpu.workloads.tcp import SutConnection

    def run_once(bad_lease):
        ports = _free_ports(3)
        procs = spawn_cluster(BINARY, ports, durable=True,
                              timeout_ms=400, elect_ms=500,
                              lease_ms=300,
                              flags=["-L"] if bad_lease else [])
        ctl = ClusterControl(ports)

        def req(port, line, timeout=1.5):
            conn = SutConnection("127.0.0.1", port, timeout_s=timeout)
            try:
                conn.connect()
                return conn.request(line)
            except TimeoutError:
                return "TIMEOUT"
            finally:
                conn.close()

        try:
            assert req(ports[0], "W 1 5").startswith("OK")
            # cut the leader off and immediately drag its clock 60s
            # backward — with -L its lease can never expire
            ctl.partition([0], [1, 2])
            assert ctl.clock(0, -60_000), "clock command never landed"
            # the majority side elects and commits a NEW value
            deadline = time.monotonic() + 6.0
            new_leader = None
            while time.monotonic() < deadline and new_leader is None:
                for info in ctl.info():
                    if info["role"] == "primary" and info["node"] != 0:
                        new_leader = info["node"]
                time.sleep(0.05)
            assert new_leader is not None, "no election"
            assert req(ports[new_leader], "W 1 7").startswith("OK")
            # read via the deposed-but-clock-frozen old leader
            stale = req(ports[0], "R 1", timeout=1.2)
            fresh = req(ports[new_leader], "R 1")
            assert fresh == "V 7"
            return stale
        finally:
            ctl.clocks_reset()
            ctl.heal()
            _kill(procs)

    # correct implementation: the deposed leader refuses to serve
    stale = run_once(bad_lease=False)
    assert stale in ("UNKNOWN", "TIMEOUT"), stale

    # -L control: the stale read escapes, and the checker flags the
    # resulting history (write 5 ok; write 7 ok; read 7; then read 5
    # strictly after — no linearization allows the register to go back)
    stale = run_once(bad_lease=True)
    assert stale == "V 5", \
        ("bad-lease leader should have served its stale register",
         stale)
    h = [Op(process=0, type="invoke", f="write", value=5, time=0),
         Op(process=0, type="ok", f="write", value=5, time=1),
         Op(process=1, type="invoke", f="write", value=7, time=2),
         Op(process=1, type="ok", f="write", value=7, time=3),
         Op(process=2, type="invoke", f="read", value=None, time=4),
         Op(process=2, type="ok", f="read", value=7, time=5),
         Op(process=3, type="invoke", f="read", value=None, time=6),
         Op(process=3, type="ok", f="read", value=5, time=7)]
    assert analysis(cas_register(), h, backend="host").valid is False


def test_five_node_cluster_breaknet_failover(tmp_path):
    """Reference scale: 5 nodes (m1-m5, comdb2/core.clj:195-208) with
    the breaknet partition shape {master, +1} vs the other three
    (nemesis.c:90-144) — at five nodes that cut denies the master
    quorum, so the majority side must elect and serve while the
    minority's writes go indeterminate; the whole history stays
    linearizable (seed 71)."""
    ports = _free_ports(5)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=300,
                          elect_ms=500, lease_ms=300)
    try:
        ctl = ClusterControl(ports)
        t = _cluster_test(
            tmp_path, ports, "cluster-5node-breaknet",
            concurrency=7,
            nemesis=ClusterPartitioner(ctl, rng=random.Random(71)),
            generator=_nemesis_gen(seed=71, secs=6.0, window=2.0,
                                   lead=0.4, gap=0.8))
        result = core.run(t)
        terms = [n.get("term", 1) for n in ctl.info()
                 if n["role"] != "down"]
        ctl.heal()
        assert result["results"]["valid?"] is True, \
            ("seed 71", result["results"])
        assert max(terms) > 1, "breaknet never forced an election"
        oks = [op for op in result["history"] if op.type == "ok"]
        assert len(oks) >= 100, len(oks)
        # converges after heal
        assert ctl.await_replicated(timeout_s=10.0), ctl.info()
    finally:
        _kill(procs)


def test_ha_client_comdb2db_discovery(tmp_path):
    """cdb2api-style cluster discovery (cdb2api.c:780-1000): the HA
    client resolves "@<cfgfile>#<dbname>" to the node list from a
    comdb2db-format config instead of taking hosts on the command
    line; the workload then runs normally over the discovered
    cluster. A missing dbname must fail fast, not fall back to the
    in-memory store."""
    import subprocess

    from comdb2_tpu.checker import analysis
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops.history import parse_history

    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=400,
                          elect_ms=500, lease_ms=300)
    cfg = tmp_path / "comdb2db.cfg"
    cfg.write_text(
        "# comdb2db-style cluster config\n"
        "otherdb 10.0.0.1:1 10.0.0.2:1\n"
        + "testdb " + " ".join(f"127.0.0.1:{p}" for p in ports) + "\n")
    out = tmp_path / "disc.edn"
    try:
        p = subprocess.run(
            [os.path.join(ROOT, "native", "build", "ct_register"),
             "-T", "3", "-r", "6", "-d", f"@{cfg}#testdb",
             "-j", str(out), "-s", "5"],
            capture_output=True, text=True, timeout=60)
        assert p.returncode == 0, p.stderr
        h = parse_history(out.read_text())
        oks = sum(1 for op in h if op.type == "ok")
        assert oks >= 20, oks
        a = analysis(cas_register(), h, backend="host")
        assert a.valid is True
        # unknown dbname: the driver must fail, not silently run
        # against nothing
        p2 = subprocess.run(
            [os.path.join(ROOT, "native", "build", "ct_register"),
             "-T", "1", "-r", "2", "-d", f"@{cfg}#nosuchdb",
             "-j", str(tmp_path / "x.edn")],
            capture_output=True, text=True, timeout=30)
        assert p2.returncode != 0
    finally:
        _kill(procs)
