"""Replicated in-tree SUT: primary + replicas over TCP with durable-LSN
majority acks, exercised by the register workload + partition nemesis.

The round-1 gap (VERDICT Missing #3): partitions could sever
client<->server but never produce a real anomaly. Here a partition
between the primary and its replicas produces — and the checker
catches — an actual stale read in `--no-durable` mode, while durable
mode stays VALID (writes that can't reach a majority surface as
indeterminate info ops, the linearizable.lrl:1-17 semantics)."""

import os
import socket

import pytest

from comdb2_tpu.checker import checkers as C
from comdb2_tpu.checker import independent as I
from comdb2_tpu.harness import core, fake
from comdb2_tpu.harness import generator as G
from comdb2_tpu.models import model as M
from comdb2_tpu.workloads import comdb2 as W
from comdb2_tpu.workloads.tcp import (ClusterControl, ClusterPartitioner,
                                      TcpClusterRegisterClient,
                                      spawn_cluster)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BINARY = os.path.join(ROOT, "native", "build", "sut_node")

pytestmark = pytest.mark.skipif(not os.path.exists(BINARY),
                                reason="sut_node not built")


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _cluster_test(tmp_path, ports, name, **kw):
    t = fake.noop_test()
    t.update({
        "nodes": [], "concurrency": 5, "name": name,
        "store-root": str(tmp_path / "store"),
        "client": TcpClusterRegisterClient(ports, timeout_s=0.45),
        "model": M.cas_register(),
        "generator": G.clients(G.limit(120, G.mix([W.r, W.w, W.cas]))),
        "checker": I.checker(C.Linearizable(backend="host")),
    })
    t.update(kw)
    return t


def test_cluster_discovery_and_replication():
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=800)
    try:
        ctl = ClusterControl(ports)
        info = ctl.info()
        assert [n["role"] for n in info] == ["primary", "replica",
                                             "replica"]
        assert ctl.primary() == 0
    finally:
        for p in procs:
            p.kill()
        for p in procs:
            p.wait()


def test_durable_cluster_valid_without_faults(tmp_path):
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=800)
    try:
        t = _cluster_test(tmp_path, ports, "cluster-register")
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
        oks = [op for op in result["history"] if op.type == "ok"]
        assert len(oks) >= 60
    finally:
        for p in procs:
            p.kill()
        for p in procs:
            p.wait()


N_KEYS = 8


def _keyed(f):
    """Spread ops over N_KEYS independent registers (the reference's
    register test is keyed the same way): every write that times out in
    a partition window stays pending forever, and the checker's config
    set is exponential in pending ops PER KEY — keying is what keeps
    fault-heavy histories verifiable (independent.clj:252-300)."""
    import random as _random

    from comdb2_tpu.ops.kv import tuple_

    def op(test=None, process=None):
        k = _random.randrange(N_KEYS)
        if f == "read":
            return {"type": "invoke", "f": "read",
                    "value": tuple_(k, None)}
        if f == "write":
            return {"type": "invoke", "f": "write",
                    "value": tuple_(k, _random.randrange(5))}
        return {"type": "invoke", "f": "cas",
                "value": tuple_(k, (_random.randrange(5),
                                    _random.randrange(5)))}
    return op


def _nemesis_gen(secs=4.0):
    """Clients run for the whole window (time-limited, not op-limited:
    an op-count budget can drain before the first partition opens) while
    the nemesis cycles two partition windows."""
    kr, kw, kc = _keyed("read"), _keyed("write"), _keyed("cas")
    return G.nemesis(
        G.seq([G.sleep(0.3), {"type": "info", "f": "start"},
               G.sleep(1.0), {"type": "info", "f": "stop"},
               G.sleep(0.6), {"type": "info", "f": "start"},
               G.sleep(1.0), {"type": "info", "f": "stop"}]),
        G.time_limit(secs, G.stagger(
            0.01, G.mix([kr, kr, kw, kc]))))


def test_durable_cluster_valid_under_partition(tmp_path):
    """Master-targeted partitions against the durable cluster: writes
    that can't reach a majority time out into info ops; the history
    stays linearizable."""
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=300)
    try:
        ctl = ClusterControl(ports)
        t = _cluster_test(
            tmp_path, ports, "cluster-nemesis-durable",
            nemesis=ClusterPartitioner(ctl, isolate_primary=True),
            generator=_nemesis_gen())
        result = core.run(t)
        ctl.heal()
        assert result["results"]["valid?"] is True, result["results"]
        infos = [op for op in result["history"]
                 if op.type == "info" and op.process != "nemesis"]
        assert infos, "partition should have produced indeterminate ops"
    finally:
        for p in procs:
            p.kill()
        for p in procs:
            p.wait()


def test_no_durable_partition_detected_invalid(tmp_path):
    """The negative control: same workload, same partitions, but the
    cluster acknowledges writes before replication (--no-durable) — a
    partitioned replica serves stale reads and the checker must flag
    the history invalid. Detection depends on which worker reads from
    which node during a window, so retry a few rounds."""
    for attempt in range(4):
        ports = _free_ports(3)
        procs = spawn_cluster(BINARY, ports, durable=False)
        try:
            ctl = ClusterControl(ports)
            t = _cluster_test(
                tmp_path, ports, f"cluster-nodurable-{attempt}",
                nemesis=ClusterPartitioner(ctl, isolate_primary=True),
                generator=_nemesis_gen())
            result = core.run(t)
            ctl.heal()
            if result["results"]["valid?"] is False:
                return
        finally:
            for p in procs:
                p.kill()
            for p in procs:
                p.wait()
    raise AssertionError(
        "no-durable cluster never produced a detectable stale "
        "read/lost write under partitions in 4 runs")
