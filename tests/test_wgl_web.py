"""Tests for the WGL world-search engine and the web store browser."""

import random
import urllib.error
import urllib.request

from comdb2_tpu.checker import wgl
from comdb2_tpu.models import model as M
from comdb2_tpu.ops.op import invoke, ok, info
from comdb2_tpu.ops.synth import register_history, mutate


def test_wgl_valid_simple():
    h = [invoke(0, "write", 1), ok(0, "write", 1),
         invoke(1, "read", 1), ok(1, "read", 1)]
    r = wgl.analysis(M.register(), h)
    assert r["valid?"] is True


def test_wgl_invalid_simple():
    h = [invoke(0, "write", 1), ok(0, "write", 1),
         invoke(1, "read", None), ok(1, "read", 2)]
    r = wgl.analysis(M.register(), h)
    assert r["valid?"] is False
    assert r["deepest-index"] < 4


def test_wgl_concurrent_reorder():
    # two concurrent writes; read sees the first-invoked one — only
    # valid if the search reorders linearization points
    h = [invoke(0, "write", 1),
         invoke(1, "write", 2),
         ok(1, "write", 2),
         ok(0, "write", 1),
         invoke(2, "read", 2), ok(2, "read", 2)]
    r = wgl.analysis(M.cas_register(), h)
    assert r["valid?"] is True


def test_wgl_pending_info_ops():
    # an indeterminate write may or may not have applied
    h = [invoke(0, "write", 1), info(0, "write", 1),
         invoke(1, "read", 1), ok(1, "read", 1)]
    assert wgl.analysis(M.register(), h)["valid?"] is True
    h2 = [invoke(0, "write", 1), info(0, "write", 1),
          invoke(1, "read", None), ok(1, "read", 5)]
    assert wgl.analysis(M.register(), h2)["valid?"] is False


def test_wgl_agrees_with_linear_engine():
    from comdb2_tpu.checker import linear

    rng = random.Random(13)
    for trial in range(25):
        h = register_history(rng, n_procs=3, n_events=30, p_info=0.1)
        if trial % 2:
            h = mutate(rng, h)
        expected = linear.analysis(M.cas_register(), h,
                                   backend="host").valid
        got = wgl.analysis(M.cas_register(), h)["valid?"]
        assert got == expected, f"trial {trial}: wgl={got} linear={expected}"


def test_wgl_overflow_unknown():
    rng = random.Random(5)
    h = register_history(rng, n_procs=4, n_events=200, p_info=0.0)
    r = wgl.analysis(M.cas_register(), h, max_worlds=10)
    assert r["valid?"] in (True, "unknown")   # tiny budget may still win


# --- web --------------------------------------------------------------------

def test_web_store_browser(tmp_path):
    from comdb2_tpu.harness import core, fake, web
    from comdb2_tpu.harness import generator as G
    from comdb2_tpu.models import model as MM

    state = fake.Atom()
    t = fake.noop_test()
    t.update({"nodes": [], "concurrency": 3, "name": "webtest",
              "store-root": str(tmp_path / "store"),
              "db": fake.atom_db(state), "client": fake.atom_client(state),
              "model": MM.cas_register(),
              "generator": G.clients(G.limit(10, G.cas_gen))})
    res = core.run(t)

    srv, port = web.serve(store_root=str(tmp_path / "store"), port=0,
                          block=False)
    try:
        base = f"http://127.0.0.1:{port}"
        idx = urllib.request.urlopen(f"{base}/").read().decode()
        assert "webtest" in idx and "True" in idx
        st = res["start-time"]
        listing = urllib.request.urlopen(
            f"{base}/files/webtest/{st}/").read().decode()
        assert "history.edn" in listing and "results.edn" in listing
        hist = urllib.request.urlopen(
            f"{base}/files/webtest/{st}/history.edn").read().decode()
        assert ":invoke" in hist
        z = urllib.request.urlopen(f"{base}/zip/webtest/{st}").read()
        assert z[:2] == b"PK"
        # traversal rejected
        try:
            urllib.request.urlopen(f"{base}/files/../../etc/passwd")
            raised = False
        except urllib.error.HTTPError as e:
            raised = e.code in (403, 404)
        assert raised
    finally:
        srv.shutdown()
