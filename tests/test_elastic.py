"""Elastic fleet (round 12, docs/service.md "Elastic fleet"):
ring-epoch membership, session checkpoint/restore/migration, drain,
supervisor lifecycle, and the routed client's failure policy.

The load-bearing contracts:

- a membership change remaps ≈1/N of the ring's keys, never a
  reshuffle;
- a checkpoint restores BIT-identical to the live carry on every
  engine rung, and a migrated-mid-session twin reaches the identical
  verdict with zero replay (per-append dispatches stay O(delta));
- a draining core re-routes its forming batches, finalizes staged
  dispatches with real replies, and keeps serving checkpoint
  handoffs;
- the supervisor reaps every child it retires (this container has no
  init reaper — an unreaped daemon is a zombie, CLAUDE.md).
"""

import os
import random
import subprocess
import time

import numpy as np
import pytest

from comdb2_tpu.obs import trace as obs
from comdb2_tpu.ops import op as O
from comdb2_tpu.ops.history import history_to_edn
from comdb2_tpu.ops.synth import (inject_anomaly, pinned_wide_history,
                                  register_history)
from comdb2_tpu.service.client import (HashRing, RoutedClient,
                                       RoutedStream, ServiceError)
from comdb2_tpu.service.core import VerifierCore
from comdb2_tpu.service.daemon import (bump_ring_epoch,
                                       epoch_service_for)
from comdb2_tpu.stream import checkpoint as CK
from comdb2_tpu.stream.session import StreamSession

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _feed(s, h, lo, hi, step=9):
    i = lo
    while i < hi:
        s.append(h[i:min(i + step, hi)])
        i += step


def _oneshot(h, model, F=1024):
    from comdb2_tpu.checker.batch import check_batch, pack_batch
    from comdb2_tpu.models.model import MODELS
    from comdb2_tpu.ops.packed import pack_history

    b = pack_batch([pack_history(list(h))], MODELS[model]())
    st, fa, nf = check_batch(b, F=F)
    return int(st[0]), int(fa[0]), int(nf[0])


# --- ring epochs -------------------------------------------------------------

def test_hash_ring_join_remaps_about_one_over_n():
    """Adding one node to an N-node ring remaps ~1/(N+1) of the keys
    — consistent hashing's whole point; a modulo ring would remap
    ~all of them. Bounded generously (md5 + 64 vnodes jitters)."""
    nodes = [f"sut/verifier/{i}" for i in range(4)]
    before = HashRing(nodes)
    after = HashRing(nodes + ["sut/verifier/4"])
    keys = [f"check|cas-register|{1 << (i % 12)}|{i}"
            for i in range(512)]
    moved = sum(before.nodes_for(k)[0] != after.nodes_for(k)[0]
                for k in keys)
    frac = moved / len(keys)
    assert 0.02 < frac < 0.45, frac          # ~0.2 expected
    # and every moved key landed on the NEW node (join never shuffles
    # keys between survivors)
    for k in keys:
        a, b = before.nodes_for(k)[0], after.nodes_for(k)[0]
        if a != b:
            assert b == "sut/verifier/4", (k, a, b)


def test_epoch_service_name_is_not_a_daemon_endpoint():
    """The epoch entry must never be discovered as a fleet member:
    RoutedClient matches ``<prefix>`` or ``<prefix>/...``; the epoch
    rides a ``.``-suffixed sibling."""
    prefix = "sut/verifier"
    for svc in (prefix, f"{prefix}/0", f"{prefix}/17"):
        assert epoch_service_for(svc) == "sut/verifier.epoch"
    e = epoch_service_for(prefix)
    assert e != prefix and not e.startswith(prefix + "/")


# --- checkpoint/restore bit parity per rung ----------------------------------

def _ck_roundtrip(s):
    """checkpoint -> wire -> restore; returns (in-process ck,
    restored session)."""
    ck = s.checkpoint()
    wire = CK.to_wire(ck)
    assert CK.wire_nbytes(wire) > 0
    return ck, StreamSession.restore(CK.from_wire(wire))


def test_checkpoint_restore_bit_parity_xla():
    h = register_history(random.Random(4), n_procs=3, n_events=120,
                         values=2, p_info=0.0, max_pending=2)
    s = StreamSession("cas-register", engine="xla")
    _feed(s, h, 0, len(h) // 2)
    ck, r = _ck_roundtrip(s)
    assert r._rung == "xla"
    for i, (a, b) in enumerate(zip(ck["eng"]["carry"],
                                   r._eng.carry)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"carry[{i}]")
    # the memo replay reproduces ids exactly (the carry stores them)
    assert r.memo.n_states == s.memo.n_states
    np.testing.assert_array_equal(r.memo.succ, s.memo.succ)
    # segment stream + renamer state identical
    assert r.seg.n_segments == s.seg.n_segments
    assert r.seg.p_eff == s.seg.p_eff
    np.testing.assert_array_equal(r.seg.inv_slot.a, s.seg.inv_slot.a)
    np.testing.assert_array_equal(r.seg.ok_slot.a, s.seg.ok_slot.a)


def test_checkpoint_restore_bit_parity_mxu():
    h = pinned_wide_history(18)
    s = StreamSession("cas-register")
    _feed(s, h, 0, len(h), step=23)
    assert s._rung == "mxu"
    ck, r = _ck_roundtrip(s)
    assert r._rung == "mxu"
    cw, rw = ck["eng"]["carry"], r._eng.carry
    for i, (a, b) in enumerate(zip(cw[0], rw[0])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"words[{i}]")
    for i in range(1, 5):
        np.testing.assert_array_equal(np.asarray(cw[i]),
                                      np.asarray(rw[i]))
    out = r.finalize_input()
    exp = _oneshot(h, "cas-register")
    assert (out["valid"] is True) == (exp[0] == 0)


@pytest.fixture()
def interpret_kernel():
    from comdb2_tpu.checker import pallas_seg as PS

    PS.use_interpret(True)
    PS.available.cache_clear()
    yield
    PS.use_interpret(False)
    PS.available.cache_clear()


def test_checkpoint_restore_bit_parity_kernel(interpret_kernel):
    """The kernel rung's (ws, stat) word carry round-trips exactly
    (interpret mode: the exact kernel as XLA ops on CPU)."""
    h1 = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
          O.invoke(1, "write", 2), O.ok(1, "write", 2),
          O.invoke(0, "read", None), O.ok(0, "read", 2)]
    h3 = [O.invoke(0, "read", None), O.ok(0, "read", 1)]  # stale
    s = StreamSession("cas-register")
    s.append(h1)
    assert s._rung == "kernel"
    ck, r = _ck_roundtrip(s)
    assert r._rung == "kernel"
    for i, (a, b) in enumerate(zip(ck["eng"]["ws"], r._eng.ws)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"ws[{i}]")
    np.testing.assert_array_equal(np.asarray(ck["eng"]["stat"]),
                                  np.asarray(r._eng.stat))
    # the restored session catches the violation the live one would
    out = r.append(h3)
    assert out["valid"] is False
    assert r.replays == 0


def test_kernel_checkpoint_restores_without_kernel_support():
    """A kernel-rung checkpoint restored where the fused kernel can't
    run (plain CPU) re-routes by replaying the retained segments —
    the same O(history) event a live crossing pays — instead of
    failing the restore."""
    from comdb2_tpu.checker import pallas_seg as PS

    PS.use_interpret(True)
    PS.available.cache_clear()
    try:
        s = StreamSession("cas-register")
        s.append([O.invoke(0, "write", 1), O.ok(0, "write", 1),
                  O.invoke(1, "read", None), O.ok(1, "read", 1)])
        assert s._rung == "kernel"
        ck = CK.to_wire(s.checkpoint())
    finally:
        PS.use_interpret(False)
        PS.available.cache_clear()
    r = StreamSession.restore(CK.from_wire(ck))
    assert r._rung in ("xla", "mxu")
    assert r.replays == 1
    out = r.append([O.invoke(0, "read", None), O.ok(0, "read", 9)])
    assert out["valid"] is False


# --- migration parity + O(delta) ---------------------------------------------

@pytest.mark.parametrize("name,h", [
    ("valid", register_history(random.Random(41), n_procs=3,
                               n_events=96, values=2, p_info=0.0,
                               max_pending=2)),
    ("invalid-tail", inject_anomaly(
        register_history(random.Random(42), n_procs=3, n_events=60),
        "stale-read")[0]),
])
def test_migration_mid_session_verdict_parity(name, h):
    h = list(h)
    twin = StreamSession("cas-register", engine="xla")
    _feed(twin, h, 0, len(h))
    exp = twin.finalize_input()
    cut = len(h) // 2
    s = StreamSession("cas-register", engine="xla")
    _feed(s, h, 0, cut)
    d_half = s.dispatches
    _ck, r = _ck_roundtrip(s)
    _feed(r, h, cut, len(h))
    out = r.finalize_input()
    assert out["valid"] == exp["valid"], (name, exp, out)
    assert out["op_index"] == exp["op_index"]
    if exp["valid"] is True:
        assert out["final_count"] == exp["final_count"]
    # O(delta) after handoff: the second half costs about what the
    # first half did — never a full-history replay
    assert out["replays"] == 0
    assert out["dispatches"] - d_half <= d_half + 2, out


# --- eviction-restore round trip through the service -------------------------

def test_core_eviction_restore_round_trip():
    h = register_history(random.Random(8), n_procs=3, n_events=48,
                         p_info=0.0, max_pending=2)
    cut = len(h) // 2
    core = VerifierCore(batch_cap=8, session_idle_s=5.0)
    now = obs.monotonic()
    _, r = core.submit({"kind": "stream", "verb": "open", "id": 1},
                       now)
    sid = r["session"]
    core.submit({"kind": "stream", "verb": "append", "id": 2,
                 "session": sid, "history": history_to_edn(h[:cut])},
                now)
    (_, rep), = core.tick(now)
    assert rep["valid"] is True
    core.pump(now + 6.0)                 # idle TTL passes -> evict
    assert core.m["stream_evicted"] == 1
    assert len(core.sessions) == 0
    assert core.sessions.checkpoint_count() == 1
    # the next append restores transparently — no unknown-session,
    # no client replay
    core.submit({"kind": "stream", "verb": "append", "id": 3,
                 "session": sid, "history": history_to_edn(h[cut:])},
                now + 7.0)
    (_, rep2), = core.tick(now + 7.0)
    assert rep2["valid"] is True, rep2
    assert rep2["replays"] == 0
    assert core.sessions.restores == 1
    _, cl = core.submit({"kind": "stream", "verb": "close", "id": 4,
                         "session": sid}, now + 8.0)
    assert cl["valid"] is True
    exp = _oneshot(h, "cas-register")
    assert (exp[0] == 0) and cl["final_count"] == exp[2]


def test_checkpoint_of_evicted_session_serves_held_snapshot():
    """``verb:"checkpoint"`` on an idle-evicted session must serve
    the HELD host snapshot — restoring just to re-snapshot would
    replay the memo extend log (and a kernel rung a device re-route)
    on the single-threaded drain path, and migration-during-drain is
    exactly when sessions sit evicted. ``release:true`` still drops
    the held entry (the MOVE's destructive half)."""
    h = register_history(random.Random(9), n_procs=3, n_events=30,
                         p_info=0.0, max_pending=2)
    core = VerifierCore(batch_cap=8, session_idle_s=5.0)
    now = obs.monotonic()
    _, r = core.submit({"kind": "stream", "verb": "open", "id": 1},
                       now)
    sid = r["session"]
    core.submit({"kind": "stream", "verb": "append", "id": 2,
                 "session": sid, "history": history_to_edn(h)}, now)
    core.tick(now)
    core.pump(now + 6.0)                 # idle TTL passes -> evict
    assert core.sessions.checkpoint_count() == 1
    _, ckr = core.submit({"kind": "stream", "verb": "checkpoint",
                          "id": 3, "session": sid, "release": True},
                         now + 7.0)
    assert ckr["ok"] and ckr["released"], ckr
    assert core.sessions.restores == 0   # served, never restored
    assert core.sessions.checkpoint_count() == 0   # MOVE completed
    # the handed-off checkpoint restores identically elsewhere
    core2 = VerifierCore(batch_cap=8)
    _, mo = core2.submit({"kind": "stream", "verb": "open", "id": 4,
                          "checkpoint": ckr["checkpoint"]},
                         now + 8.0)
    assert mo["ok"] and mo["migrated"], mo
    _, cl = core2.submit({"kind": "stream", "verb": "close", "id": 5,
                          "session": mo["session"]}, now + 9.0)
    assert cl["valid"] is True
    exp = _oneshot(h, "cas-register")
    assert (exp[0] == 0) and cl["final_count"] == exp[2]


# --- drain -------------------------------------------------------------------

def test_drain_finalizes_staged_and_reroutes_forming():
    """Under drain: requests already STAGED in the in-flight ring
    finalize with real verdicts; requests still FORMING answer
    shutting-down (the client re-routes); new work sheds; the
    checkpoint handoff verbs keep working."""
    core = VerifierCore(batch_cap=2, F=64)
    now = obs.monotonic()

    def sub(i, n_events, seed):
        h = register_history(random.Random(seed), 3, n_events,
                             p_info=0.0)
        return core.submit({"op": "check", "id": i,
                            "history": history_to_edn(h)}, now)

    # two same-bucket requests (identical shape: same seed) hit the
    # cap -> staged into the ring inside submit (launch_full); a
    # third (different size class) stays forming
    p1, r1 = sub(1, 24, 0)
    p2, r2 = sub(2, 24, 0)
    assert r1 is None and r2 is None
    assert core.inflight() == 1, "batch did not stage"
    p3, r3 = sub(3, 180, 2)
    assert r3 is None and core.queue_depth() == 1
    _, dr = core.submit({"kind": "drain", "id": 99}, now)
    assert dr["ok"] and dr["draining"] and dr["flushed"] == 1
    replies = {rep.get("id"): rep for _, rep in core.pump(now)}
    # the staged pair finalized with real verdicts...
    assert replies[1]["ok"] and replies[1]["valid"] is True
    assert replies[2]["ok"] and replies[2]["valid"] is True
    # ...the forming one re-routed
    assert replies[3]["ok"] is False
    assert replies[3]["error"] == "shutting-down"
    assert core.drained()
    # new work sheds; the metrics scrape still answers
    _, shed = sub(4, 24, 3)
    assert shed["error"] == "shutting-down" and shed["draining"]
    _, m = core.submit({"kind": "metrics", "id": 5}, now)
    assert m is None or m["ok"]


def test_drain_serves_checkpoint_handoff():
    h = register_history(random.Random(9), n_procs=3, n_events=40,
                         p_info=0.0, max_pending=2)
    core = VerifierCore(batch_cap=8)
    now = obs.monotonic()
    _, r = core.submit({"kind": "stream", "verb": "open", "id": 1},
                       now)
    sid = r["session"]
    core.submit({"kind": "stream", "verb": "append", "id": 2,
                 "session": sid, "history": history_to_edn(h)}, now)
    core.tick(now)
    core.submit({"kind": "drain", "id": 3}, now)
    # append sheds, checkpoint (the handoff) works and releases
    _, shed = core.submit({"kind": "stream", "verb": "append",
                           "id": 4, "session": sid,
                           "history": history_to_edn(h)}, now)
    assert shed["error"] == "shutting-down"
    _, ckr = core.submit({"kind": "stream", "verb": "checkpoint",
                          "id": 5, "session": sid, "release": True},
                         now)
    assert ckr["ok"] and ckr["checkpoint_bytes"] > 0
    assert len(core.sessions) == 0 and core.drained()
    # ...and restores on a fresh (new-owner) core with the verdict
    # intact
    core2 = VerifierCore(batch_cap=8)
    _, mo = core2.submit({"kind": "stream", "verb": "open", "id": 6,
                          "checkpoint": ckr["checkpoint"]}, now)
    assert mo["ok"] and mo["migrated"], mo
    assert core2.m["stream_migrations"] == 1
    pm = core2.metrics_reply()["prometheus"]
    for metric in ("ring_epoch", "stream_migrations",
                   "checkpoint_bytes"):
        assert metric in pm, metric


# --- routed-client failure policy --------------------------------------------

class _StubClient:
    def __init__(self, fail=None):
        self.calls = 0
        self.fail = fail                   # None | OSError | reply

    def check(self, history, **kw):
        self.calls += 1
        if isinstance(self.fail, Exception):
            raise self.fail
        if self.fail is not None:
            raise ServiceError.from_reply(self.fail)
        return {"ok": True, "valid": True}

    def close(self):
        pass


def _two_node_routed(a, b):
    rc = RoutedClient({"sut/verifier/0": a, "sut/verifier/1": b})
    return rc


def _key_owned_by(rc, owner):
    for i in range(256):
        key = f"k{i}"
        if rc.ring.nodes_for(key)[0] == owner:
            return key
    raise AssertionError("no key hashed to the node")


def test_blacklist_skips_dead_node_within_ttl():
    a, b = _StubClient(fail=OSError("down")), _StubClient()
    rc = _two_node_routed(a, b)
    rc.blacklist_ttl_s = 0.2
    key = _key_owned_by(rc, "sut/verifier/0")
    assert rc._route(key, lambda c: c.check("h"))["ok"]
    assert a.calls == 1 and rc.failovers == 1
    # within the TTL the dead node is NOT re-dialed
    assert rc._route(key, lambda c: c.check("h"))["ok"]
    assert a.calls == 1
    # after the TTL it gets another chance (it recovered)
    a.fail = None
    time.sleep(0.25)
    assert rc._route(key, lambda c: c.check("h"))["ok"]
    assert a.calls == 2


def test_failover_honors_retry_after_ms():
    """An overloaded owner parks for ITS OWN retry_after_ms hint and
    the request fails over to the next ring node — previously only
    the happy path backed off (and the walk would re-dial the
    overloaded node on every request)."""
    a = _StubClient(fail={"ok": False, "error": "overload",
                          "retry_after_ms": 150})
    b = _StubClient()
    rc = _two_node_routed(a, b)
    key = _key_owned_by(rc, "sut/verifier/0")
    assert rc._route(key, lambda c: c.check("h"))["ok"]
    assert a.calls == 1 and b.calls == 1
    # parked: the hint window keeps the walk off the overloaded node
    assert rc._route(key, lambda c: c.check("h"))["ok"]
    assert a.calls == 1 and b.calls == 2
    time.sleep(0.16)
    a.fail = None
    assert rc._route(key, lambda c: c.check("h"))["ok"]
    assert a.calls == 2


def test_shutting_down_reply_fails_over():
    a = _StubClient(fail={"ok": False, "error": "shutting-down"})
    b = _StubClient()
    rc = _two_node_routed(a, b)
    key = _key_owned_by(rc, "sut/verifier/0")
    out = rc._route(key, lambda c: c.check("h"))
    assert out["ok"] and b.calls == 1
    assert rc.failovers == 1


def test_refresh_parks_pinned_nodes_for_handoff(monkeypatch):
    """A refresh that drops a node with streams PINNED to it must
    park the warm client instead of closing it: a draining daemon
    serves checkpoint handoffs only over already-open connections
    (its listener is closed) — closing here would degrade the
    O(carry) migration to a full retained-delta replay whenever any
    unrelated routed request refreshes during the drain grace."""
    a, b = _StubClient(), _StubClient()
    closed = []
    a.close = lambda: closed.append("a")
    a.port, b.port = 7000, 7001
    rc = _two_node_routed(a, b)
    rc._disco = ("127.0.0.1", 5105, "sut/verifier", {})

    class _FakePmux:
        def __init__(self, *args, **kw):
            pass

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

        def used(self):
            return {"sut/verifier/1": 7001}

    import comdb2_tpu.control.pmux as pmux_mod
    monkeypatch.setattr(pmux_mod, "PmuxClient", _FakePmux)
    rc._pin("sut/verifier/0")            # one open RoutedStream
    added, removed = rc.refresh()
    assert removed == ["sut/verifier/0"] and not closed
    assert rc._parting["sut/verifier/0"] is a
    assert "sut/verifier/0" not in rc.clients
    # the pinned handle still resolves its daemon for the handoff
    rs = RoutedStream.__new__(RoutedStream)
    rs.routed, rs.node = rc, "sut/verifier/0"
    assert rs._client() is a
    # unpinned (migrated / closed): the parked client finally closes
    rc._unpin("sut/verifier/0")
    assert closed == ["a"] and not rc._parting


def test_memo_overflow_leaves_checkpoint_replayable():
    """An extend that overflows ``max_states`` latches the session
    terminal-UNKNOWN, but the session stays checkpointable — the
    extend-call log must record only the SUCCESSFUL extends, or
    every restore of that checkpoint would replay the overflow and
    raise (a spurious error instead of the latched verdict; on the
    release-based migration path the session would be lost
    outright)."""
    from comdb2_tpu.models.memo import IncrementalMemo, MemoOverflow
    from comdb2_tpu.models.model import MODELS

    inc = IncrementalMemo(MODELS["cas-register"](), max_states=4)
    inc.extend([("write", 1)], 1)
    n_ok = inc.n_states
    with pytest.raises(MemoOverflow):
        inc.extend([("write", 2), ("write", 3), ("write", 4),
                    ("write", 5)], 4)
    ck = inc.checkpoint()
    restored = IncrementalMemo.restore(MODELS["cas-register"](), ck)
    assert restored.transitions == [("write", 1)]
    assert restored.n_states == n_ok


# --- wire codec --------------------------------------------------------------

def test_checkpoint_wire_codec_roundtrip():
    doc = {
        "arr": np.arange(12, dtype=np.int32).reshape(3, 4),
        "flags": np.array([True, False]),
        "tup": (1, ("cas", (0, 1)), None),
        "table": [("write", 1), ("cas", (1, 2))],
        "intkeys": {3: 7, 9: 1},
        "nested": {"x": [np.int32(5), "s", 2.5]},
    }
    back = CK.from_wire(CK.to_wire(doc))
    np.testing.assert_array_equal(back["arr"], doc["arr"])
    assert back["arr"].dtype == np.int32
    np.testing.assert_array_equal(back["flags"], doc["flags"])
    assert back["tup"] == (1, ("cas", (0, 1)), None)
    assert back["table"] == [("write", 1), ("cas", (1, 2))]
    assert back["intkeys"] == {3: 7, 9: 1}
    assert back["nested"]["x"][0] == 5


# --- supervisor --------------------------------------------------------------

def test_supervisor_policy_pure():
    from comdb2_tpu.service.supervisor import desired_count

    # idle stays put at the floor
    assert desired_count(1, 0, 0, 0) == 1
    # 10 s of backlog at the observed drain rate -> scale up
    assert desired_count(1, 100, 10, 0) == 2
    # capped at max
    assert desired_count(4, 1000, 1, 0, max_daemons=4) == 4
    # drained + no sessions -> scale down, floored at min
    assert desired_count(2, 0, 10, 0) == 1
    assert desired_count(1, 0, 10, 0) == 1
    # session pressure scales up even with an empty queue
    assert desired_count(1, 0, 10, 48, max_sessions=64) == 2
    # resident sessions block scale-down (their carries live there)
    assert desired_count(2, 0, 10, 60, max_sessions=64) == 2


def test_supervisor_spawn_retire_reap_no_zombies():
    """The lifecycle contract end to end: spawn a real daemon, scrape
    it, retire it (drain -> wait), and verify the child is REAPED —
    not a zombie (no init reaper in this container)."""
    from comdb2_tpu.service.supervisor import Supervisor

    # the spawned daemon forces the cpu backend through the config
    # API (--backend cpu); the suite env already carries
    # JAX_PLATFORMS=cpu for subprocesses
    sup = Supervisor(pmux_port=None, min_daemons=1, max_daemons=2,
                     daemon_args=["--backend", "cpu", "--no-prime",
                                  "--frontier", "64"],
                     drain_grace_s=3.0)
    child = sup.spawn()
    pid = child.proc.pid
    try:
        stats = sup.scrape()
        assert stats and stats[0]["queue_depth"] == 0
        summary = sup.beat()
        assert summary["daemons"] == 1
    finally:
        sup.shutdown()
    assert child.proc.returncode is not None
    if os.path.exists(f"/proc/{pid}/stat"):
        state = open(f"/proc/{pid}/stat").read().split()[2]
        assert state != "Z", "retired daemon left a zombie"
    assert sup.retired == 1 and len(sup.children) == 0
