"""Chunked device execution + progress callbacks, the sharding module
(formerly ``comdb2_tpu.parallel``), and repl helpers."""

import random

from comdb2_tpu.service import sharding as parallel
from comdb2_tpu.checker import analysis
from comdb2_tpu.models import model as M
from comdb2_tpu.ops.synth import register_history, mutate


def test_chunked_device_matches_plain():
    rng = random.Random(21)
    for trial in range(4):
        h = register_history(rng, n_procs=3, n_events=300, p_info=0.05)
        if trial % 2:
            h = mutate(rng, h)
        plain = analysis(M.cas_register(), h, backend="device")
        calls = []
        chunked = analysis(
            M.cas_register(), h, backend="device",
            progress=lambda d, s, n, st: calls.append((d, s, n, st)),
            progress_interval_s=0.0)
        assert chunked.valid == plain.valid
        if chunked.valid is False:
            assert chunked.op_index == plain.op_index
        # with interval 0 every chunk reports; 300 events fit one chunk
        # boundary at least when valid
        if chunked.valid is True:
            assert calls
            d, s, n, st = calls[-1]
            assert d <= s and n >= 1
            # telemetry parity: visited/s + estimated cost ride along
            # (knossos core.clj:442-460, linear/config.clj:374-393)
            assert st["visited_per_s"] > 0
            assert st["segs_per_s"] > 0
            assert st["est_cost"] >= 0


def test_progress_not_called_without_interval():
    rng = random.Random(5)
    h = register_history(rng, n_procs=3, n_events=200, p_info=0.0)
    calls = []
    a = analysis(M.cas_register(), h, backend="device",
                 progress=lambda *a_: calls.append(a_),
                 progress_interval_s=3600.0)
    assert a.valid is True
    assert calls == []      # interval never elapsed


def test_parallel_sharded_check():
    import jax

    rng = random.Random(3)
    hs = [register_history(rng, n_procs=3, n_events=40, p_info=0.0)
          for _ in range(16)]
    mesh = parallel.make_mesh(len(jax.devices()))
    status, fail_at, n = parallel.check_histories_sharded(
        hs, M.cas_register(), mesh=mesh, F=64)
    assert status.shape == (16,)
    assert (status == 0).all()


def test_parallel_sharded_uneven_batch():
    """A history count not divisible by the device count must pad and
    slice, not crash."""
    rng = random.Random(4)
    hs = [register_history(rng, n_procs=3, n_events=40, p_info=0.0)
          for _ in range(10)]
    status, fail_at, n = parallel.check_histories_sharded(
        hs, M.cas_register(), F=64)
    assert status.shape == (10,)
    assert (status == 0).all()


def test_repl_last_test_and_recheck(tmp_path):
    from comdb2_tpu.checker import checkers as C
    from comdb2_tpu.harness import core, fake, repl
    from comdb2_tpu.harness import generator as G

    state = fake.Atom()
    t = fake.noop_test()
    t.update({"nodes": [], "concurrency": 3, "name": "repl-test",
              "store-root": str(tmp_path / "store"),
              "db": fake.atom_db(state),
              "client": fake.atom_client(state),
              "model": M.cas_register(),
              "generator": G.clients(G.limit(12, G.cas_gen))})
    core.run(t)
    loaded = repl.last_test("repl-test", str(tmp_path / "store"))
    assert loaded is not None
    r = repl.recheck(loaded, C.linearizable, M.cas_register())
    assert r["valid?"] is True

    out = tmp_path / "report.txt"
    with repl.to_file(str(out)):
        print("report body")
    assert out.read_text() == "report body\n"
