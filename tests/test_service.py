"""The verifier service: shape bucketing, request coalescing,
deadlines/backpressure/degradation, the TCP daemon end to end, and
the store artifact of service runs.

The core tests drive :class:`VerifierCore` in-process (the daemon is
a thin selector loop over it); one test boots the real daemon
subprocess and exercises the wire path including a client disconnect
mid-request and a clean shutdown."""

import json
import os
import random
import socket
import subprocess
import sys
import time

import pytest

from comdb2_tpu.checker import linear
from comdb2_tpu.models import model as M
from comdb2_tpu.ops import op as O
from comdb2_tpu.ops.history import history_to_edn
from comdb2_tpu.ops.packed import pack_history
from comdb2_tpu.ops.synth import register_history
from comdb2_tpu.service import ServiceLimits, VerifierCore, bucket_for

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _core(**kw):
    kw.setdefault("F", 64)
    kw.setdefault("batch_cap", 8)
    return VerifierCore(**kw)


def _submit(core, h, **fields):
    return core.submit({"op": "check",
                        "history": history_to_edn(list(h)),
                        **fields}, time.monotonic())


INVALID = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
           O.invoke(1, "read", None), O.Op(1, "ok", "read", 2)]


# --- bucketing ---------------------------------------------------------------

def test_bucket_axes_quantized():
    h = register_history(random.Random(0), 3, 40, p_info=0.0)
    b = bucket_for(pack_history(list(h)), ServiceLimits())
    for axis in (b.n_pad, b.S, b.K, b.P):
        assert axis & (axis - 1) == 0, b   # pow2 quantization
    # effective slots: even-bucketed inside the kernel's (8,128) tier
    assert b.P_eff % 2 == 0 or b.P_eff > 7
    assert b.key == \
        f"n{b.n_pad}-s{b.S}-k{b.K}-p{b.P}-e{b.P_eff}"
    # the admission pass caches the exact segment stream for dispatch
    packed = pack_history(list(h))
    bucket_for(packed, ServiceLimits())
    assert getattr(packed, "_segments_exact", None) is not None


def test_bucket_rejects_over_limits():
    # 9 concurrent pending invokes before the first ok: K=9 exceeds
    # the kernel-derived cap -> host route
    wide = [O.invoke(i, "write", i) for i in range(9)]
    wide += [O.ok(i, "write", i) for i in range(9)]
    assert bucket_for(pack_history(list(wide)),
                      ServiceLimits()) is None
    # and a bucketed history stays bucketed
    h = register_history(random.Random(1), 3, 24, p_info=0.0)
    assert bucket_for(pack_history(list(h)),
                      ServiceLimits()) is not None


# --- coalescing + shared programs --------------------------------------------

def test_mixed_sizes_coalesce_and_share_programs():
    """Different raw sizes landing in one bucket ride ONE dispatch,
    and a later same-shape tick reuses the compiled program."""
    core = _core()
    # same generator params, different seeds: same bucket by
    # construction of the quantization (sizes differ only sub-pow2)
    pairs = [(11, 12), (13, 14)]
    keys = set()
    for seed_a, seed_b in pairs:
        ha = register_history(random.Random(seed_a), 3, 40, p_info=0.0)
        hb = register_history(random.Random(seed_b), 3, 40, p_info=0.0)
        ba = bucket_for(pack_history(list(ha)), core.limits)
        bb = bucket_for(pack_history(list(hb)), core.limits)
        if ba != bb:
            continue                      # seed landed a different K
        keys.add(ba.key)
        p1, r1 = _submit(core, ha)
        p2, r2 = _submit(core, hb)
        assert r1 is None and r2 is None  # queued, not immediate
        done = core.tick()
        assert len(done) == 2
        for _, reply in done:
            assert reply["valid"] is True
            assert reply["batched"] == 2
            assert reply["bucket"] == ba.key
    assert keys, "no seed pair shared a bucket — fixture broke"
    st = core.status()
    for key in keys:
        bs = st["buckets"][key]
        # both ticks of a shared bucket ran the same program: one
        # compile, then hits
        assert bs["dispatches"] >= 1
        assert bs["compiles"] <= 1 or bs["dispatches"] == bs["compiles"]
    if len(keys) == 1 and st["buckets"][next(iter(keys))][
            "dispatches"] == 2:
        assert st["program_hits"] >= 1


def test_verdict_matches_host_oracle():
    core = _core()
    exp = linear.analysis(M.cas_register(), list(INVALID),
                          backend="host")
    assert exp.valid is False
    _submit(core, INVALID)
    ((_, reply),) = core.tick()
    assert reply["valid"] is False
    assert reply["op_index"] == exp.op_index


# --- deadlines, backpressure, degradation ------------------------------------

def test_deadline_expired_answers_unknown_without_blocking():
    core = _core()
    h = register_history(random.Random(2), 3, 24, p_info=0.0)
    _submit(core, h, deadline_ms=0)       # expired on arrival
    _submit(core, h)
    time.sleep(0.002)
    done = core.tick()
    by_valid = {}
    for _, reply in done:
        by_valid.setdefault(str(reply["valid"]), reply)
    assert by_valid["unknown"]["cause"] == "deadline"
    assert by_valid["True"]["batched"] == 1   # batch ran without it
    assert core.m["deadline_expired"] == 1


def test_overload_is_explicit_and_immediate():
    core = _core(max_queue=2)
    h = register_history(random.Random(3), 3, 24, p_info=0.0)
    assert _submit(core, h)[1] is None
    assert _submit(core, h)[1] is None
    _, reply = _submit(core, h)
    assert reply["ok"] is False and reply["error"] == "overload"
    # the backoff hint: derived from queue depth + drain rate,
    # clamped to [25 ms, 5 s]
    assert 25 <= reply["retry_after_ms"] <= 5000
    assert core.m["overloads"] == 1
    core.tick()                            # queued two still answer


def test_over_k_history_degrades_to_host_with_same_verdict():
    core = _core()
    wide = [O.invoke(i, "write", i) for i in range(9)]
    wide += [O.ok(i, "write", i) for i in range(9)]
    exp = linear.analysis(M.cas_register(), list(wide), backend="host")
    pending, reply = _submit(core, wide)
    assert reply is None and pending.bucket is None
    ((_, reply),) = core.tick()
    assert reply["engine"] == "host" and reply["degraded"]
    assert reply["valid"] == exp.valid
    assert core.m["host_degraded"] == 1


def test_malformed_and_trivial_histories_answer_immediately():
    core = _core()
    # double-pending process WITH a completion: malformed -> unknown
    mal = [O.invoke(0, "write", 1), O.invoke(0, "write", 2),
           O.ok(0, "write", 1)]
    _, reply = _submit(core, mal)
    assert reply["valid"] == "unknown"
    assert "malformed" in reply["cause"]
    # no ok-completions: nothing constrains the frontier
    _, reply = _submit(core, [O.invoke(0, "write", 1)])
    assert reply["valid"] is True and reply["engine"] == "trivial"
    # garbage text: bad-request, not an exception
    _, reply = core.submit({"op": "check", "history": "]not edn["},
                           time.monotonic())
    assert reply["ok"] is False and reply["error"] == "bad-request"
    assert not core.queue


def test_prime_warms_programs_for_matching_traffic():
    core = _core()
    n = core.prime(specs=((24, 2),), seed=41)
    assert n >= 1
    st = core.status()
    assert st["primed"] == n and st["compiles"] >= 1
    assert st["completed"] == 0            # priming isn't traffic
    # identical-shape traffic (same generator, same seed) hits the
    # primed program instead of compiling
    h = register_history(random.Random(41), 3, 24, p_info=0.0)
    _submit(core, h)
    _submit(core, h)
    core.tick()
    st2 = core.status()
    assert st2["compiles"] == st["compiles"]
    assert st2["program_hits"] >= 1


# --- the wire ----------------------------------------------------------------

def _spawn_daemon(*extra):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [sys.executable, "-m", "comdb2_tpu.service", "--port", "0",
         "--backend", "cpu", "--no-prime", "--frontier", "64",
         "--coalesce-ms", "2", *extra],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=ROOT, env=env)
    ready = json.loads(proc.stdout.readline())
    assert ready.get("ready"), ready
    return proc, ready["port"]


def test_daemon_end_to_end(tmp_path):
    from comdb2_tpu.service.client import ServiceClient, ServiceError

    proc, port = _spawn_daemon()
    try:
        c = ServiceClient("127.0.0.1", port, timeout_s=300.0)
        h = register_history(random.Random(5), 3, 40, p_info=0.0)
        r = c.check(h)
        assert r["ok"] and r["valid"] is True
        r = c.check(INVALID)
        assert r["valid"] is False and r["op_index"] == 3
        # unknown model -> ServiceError, daemon alive
        with pytest.raises(ServiceError):
            c.check(h, model="no-such-model")
        # disconnect mid-request: reply dropped, daemon keeps serving
        s = socket.create_connection(("127.0.0.1", port))
        s.sendall((json.dumps(
            {"op": "check", "history": history_to_edn(h)}) +
            "\n").encode())
        s.close()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            st = c.status()["status"]
            if st["dropped_replies"] >= 1:
                break
            time.sleep(0.05)
        assert st["dropped_replies"] >= 1
        assert c.ping()
        assert c.check(h)["valid"] is True
        st = c.status()["status"]
        assert st["accepted"] >= 4 and st["dispatches"] >= 3
        assert st["latency_ms"]["p50"] > 0
        # filetest --service round-trips the same daemon
        edn = tmp_path / "hist.edn"
        edn.write_text(history_to_edn(h))
        r = subprocess.run(
            [sys.executable, "-m", "comdb2_tpu.filetest", str(edn),
             "--service", f"127.0.0.1:{port}"],
            cwd=ROOT, env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "'valid': True" in r.stdout
        assert c.shutdown()
    finally:
        try:
            rc = proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            proc.kill()           # never leak a daemon into the suite
            proc.wait(timeout=30)
            raise
    assert rc == 0


def test_bench_service_quick():
    """The bench script's structural assertions (dispatch-count bound,
    overload replies, disconnect survival) on a small CPU run."""
    out = os.path.join(ROOT, "tests", "_bench_service_quick.json")
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts",
                                          "bench_service.py"),
             "--quick", "--out", out],
            cwd=ROOT, env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=600)
        assert r.returncode == 0, r.stdout + r.stderr
        with open(out) as fh:
            res = json.loads(fh.read())
        assert res["burst_dispatches"] <= res["requests"]
        assert res["burst"]["latency_p99_ms"] >= \
            res["burst"]["latency_p50_ms"] > 0
        assert res["overload_replies"] >= 1
        assert res["survived_disconnect"] is True
        # the obs plane rides the bench: per-stage histograms from
        # the scrape, per-reply stage sums checked, trace artifact
        assert set(res["stages_ms"]) == {"queue_wait", "host_pack",
                                         "device", "finalize"}
        assert res["stages_ms"]["device"]["count"] > 0
        assert res["stage_sum_checked"] >= 1
        assert res["trace"]["events"] > 0
    finally:
        if os.path.exists(out):
            os.unlink(out)


# --- store artifact ----------------------------------------------------------

def test_store_service_status_artifact(tmp_path):
    from comdb2_tpu.harness.store import save_service_status

    core = _core()
    p = save_service_status(core.status(), store_root=str(tmp_path))
    p = save_service_status(core.status(), store_root=str(tmp_path))
    with open(p) as fh:
        latest = json.loads(fh.read())
    assert latest["queue_depth"] == 0
    with open(os.path.join(str(tmp_path), "service",
                           "status.jsonl")) as fh:
        assert len(fh.readlines()) == 2


# --- the parallel shim -------------------------------------------------------

def test_parallel_shim_reexports_sharding():
    import comdb2_tpu.parallel as shim
    from comdb2_tpu.service import sharding

    assert shim.make_mesh is sharding.make_mesh
    assert shim.check_histories_sharded is \
        sharding.check_histories_sharded


# --- the txn (serializability) request kind ---------------------------------
#
# Same queue, same tick, same overload/deadline answers as the check
# kind; the device work is the matrix-closure engine, coalesced per
# pow2 txn-count bucket.

from comdb2_tpu.ops.synth import (list_append_history,
                                  txn_anomaly_history)
from comdb2_tpu.service.bucketing import TxnBucket, txn_bucket_for


def _submit_txn(core, h, **fields):
    return core.submit({"op": "check", "kind": "txn",
                        "history": history_to_edn(list(h)),
                        **fields}, time.monotonic())


def test_txn_bucket_quantized_and_limited():
    limits = ServiceLimits()
    assert txn_bucket_for(3, limits) == TxnBucket(N=16)
    assert txn_bucket_for(17, limits) == TxnBucket(N=32)
    assert txn_bucket_for(limits.max_txns + 1, limits) is None
    assert TxnBucket(N=64).key == "txn-n64"


def test_txn_requests_coalesce_and_classify():
    core = _core()
    p1, r1 = _submit_txn(core, txn_anomaly_history("g2-item"))
    p2, r2 = _submit_txn(core, list_append_history(
        random.Random(3), 3, 10, 2))
    assert r1 is None and r2 is None
    assert p1.bucket == p2.bucket == TxnBucket(N=16)
    done = core.tick()
    assert len(done) == 2
    bad = next(r for _, r in done if r["valid"] is False)
    good = next(r for _, r in done if r["valid"] is True)
    assert bad["anomaly_class"] == "G2-item"
    assert bad["batched"] == 2 and bad["engine"] == "closure"
    assert [s["edge"]["type"] for s in bad["cycle"]] == ["rw", "rw"]
    assert good["kind"] == "txn"
    st = core.status()
    assert st["buckets"]["txn-n16"]["dispatches"] == 1
    assert st["buckets"]["txn-n16"]["batched"] == 2


def test_txn_and_check_kinds_share_one_tick():
    core = _core()
    _submit(core, register_history(random.Random(0), 3, 24,
                                   p_info=0.0))
    _submit_txn(core, txn_anomaly_history("clean"))
    done = core.tick()
    kinds = sorted(r.get("kind", "check") for _, r in done)
    assert kinds == ["check", "txn"]


def test_txn_program_reuse_across_ticks():
    core = _core()
    for seed in (1, 2):
        _submit_txn(core, list_append_history(
            random.Random(seed), 3, 10, 2))
        done = core.tick()
        assert done[-1][1]["valid"] is True
    bs = core.status()["buckets"]["txn-n16"]
    assert bs["dispatches"] == 2 and bs["compiles"] == 1
    assert core.m["program_hits"] >= 1


def test_txn_deadline_parity():
    core = _core()
    _submit_txn(core, txn_anomaly_history("g2-item"), deadline_ms=0)
    time.sleep(0.002)
    ((_, reply),) = core.tick()
    assert reply["valid"] == "unknown" and reply["cause"] == "deadline"
    assert core.m["deadline_expired"] == 1
    _, bad = _submit_txn(core, txn_anomaly_history("g2-item"),
                         deadline_ms="soon")
    assert bad == {"ok": False, "error": "bad-request",
                   "message": bad["message"]}


def test_txn_overload_parity():
    core = _core(max_queue=1)
    assert _submit_txn(core, txn_anomaly_history("g2-item"))[1] is None
    _, reply = _submit_txn(core, txn_anomaly_history("g2-item"))
    assert reply["ok"] is False and reply["error"] == "overload"
    assert 25 <= reply["retry_after_ms"] <= 5000
    assert core.m["overloads"] == 1
    # and a check-kind request sheds identically at the shared cap
    _, reply = _submit(core, register_history(random.Random(1), 3, 24,
                                              p_info=0.0))
    assert reply["error"] == "overload"


def test_txn_trivial_and_direct_anomalies_answer_immediately():
    core = _core()
    # edge-free but anomalous: a doubled value nobody ever appended
    # leaves no edges, so no cycle engine runs — yet the verdict is
    # already decided at admission
    h = [O.invoke(0, "txn", (("r", 0, None),)),
         O.Op(0, "ok", "txn", (("r", 0, (1, 1)),))]
    _, reply = _submit_txn(core, h)
    assert reply is not None and reply["valid"] is False
    assert "duplicate" in reply["anomalies"]
    assert reply["engine"] == "trivial"
    # edge-free and clean: immediate valid
    h = [O.invoke(0, "txn", (("append", 0, 1),)),
         O.Op(0, "ok", "txn", (("append", 0, 1),))]
    _, reply = _submit_txn(core, h)
    assert reply is not None and reply["valid"] is True


def test_txn_over_limit_degrades_to_host_scc():
    core = _core(limits=ServiceLimits(max_txns=2))
    p, reply = _submit_txn(core, txn_anomaly_history("g2-item"))
    assert reply is None and p.bucket is None
    ((_, reply),) = core.tick()
    assert reply["engine"] == "host" and reply["degraded"]
    assert reply["valid"] is False
    assert reply["anomaly_class"] == "G2-item"
    assert core.m["host_degraded"] == 1


def test_txn_malformed_answers_unknown_or_bad_request():
    core = _core()
    # double-pending process: malformed -> unknown (same contract as
    # the check kind's pack failures)
    h = [O.invoke(0, "txn", (("append", 0, 1),)),
         O.invoke(0, "txn", (("append", 0, 2),))]
    _, reply = _submit_txn(core, h)
    assert reply["valid"] == "unknown"
    assert "malformed" in reply["cause"]
    # garbage EDN -> bad-request
    _, reply = core.submit({"op": "check", "kind": "txn",
                            "history": "{:not-an-op"},
                           time.monotonic())
    assert reply["error"] == "bad-request"


def test_txn_realtime_flag_strictens():
    # serializable but NOT strictly so: t1's read is STALE — it ran
    # wholly after t0's append committed yet observed nothing, so the
    # only valid serialization (t1 before t0) contradicts realtime
    h = [O.invoke(0, "txn", (("append", 0, 7),)),
         O.Op(0, "ok", "txn", (("append", 0, 7),)),
         O.invoke(1, "txn", (("r", 0, None),)),
         O.Op(1, "ok", "txn", (("r", 0, ()),)),
         O.invoke(2, "txn", (("r", 0, None),)),
         O.Op(2, "ok", "txn", (("r", 0, (7,)),))]
    core = _core()
    p, r = _submit_txn(core, h)
    if r is None:
        ((_, r),) = core.tick()
    assert r["valid"] is True, r
    p, r2 = _submit_txn(core, h, realtime=True)
    if r2 is None:
        ((_, r2),) = core.tick()
    assert r2["valid"] is False, r2     # rw against realtime order


def test_txn_partially_malformed_answers_unknown_from_batch():
    """A history WITH edges plus one unparseable micro-op must answer
    unknown from the coalesced dispatch path — identical to what
    check_txn answers on every other surface (review regression)."""
    h = list(txn_anomaly_history("clean"))
    h += [O.invoke(9, "txn", (("x", 0, 1),)),
          O.Op(9, "ok", "txn", (("x", 0, 1),))]
    core = _core()
    p, r = _submit_txn(core, h)
    assert r is None                     # queued: the graph has edges
    ((_, reply),) = core.tick()
    assert reply["valid"] == "unknown", reply
    assert reply["malformed_ops"] == 1
    assert "malformed" in reply["cause"]
    from comdb2_tpu.txn import check_txn
    assert check_txn(h, backend="host")["valid?"] == "unknown"


def test_txn_deadline_reply_carries_kind():
    core = _core()
    _submit_txn(core, txn_anomaly_history("g2-item"), deadline_ms=0)
    time.sleep(0.002)
    ((_, reply),) = core.tick()
    assert reply["kind"] == "txn" and reply["cause"] == "deadline"
