"""The counterexample minimizer (comdb2_tpu.shrink).

Contracts under test:

- pair atomicity: atoms are invoke/complete pairs (never half-ops),
  ``:info`` ops stay pinned, candidate masks slice to well-formed
  histories that agree with a fresh per-op pack;
- 1-minimality: every single-pair removal of the output flips the
  verdict — checked against the independent HOST engine, not the
  device path that produced the result;
- exact-minimum recovery: ``inject_anomaly``'s seeded violations
  (known ground-truth minimal op sets) are recovered exactly;
- txn axis: the minimal set is a real cycle and 1-minimal per the
  host SCC oracle; direct-anomaly (acyclic) seeds answer immediately;
- seed rejection: VALID and UNKNOWN seeds raise, they never loop;
- the service ``kind:"shrink"`` round-trip incl. deadline best-so-far
  (``partial``) and the store artifacts of ``filetest --shrink``.
"""

import os
import random
import time

import numpy as np
import pytest

from comdb2_tpu.checker import linear
from comdb2_tpu.checker import linear_jax as LJ
from comdb2_tpu.models.model import MODELS
from comdb2_tpu.ops import op as O
from comdb2_tpu.ops.columnar import subset_packed
from comdb2_tpu.ops.history import history_to_edn
from comdb2_tpu.ops.packed import pack_history
from comdb2_tpu.ops.synth import (ANOMALY_KINDS, inject_anomaly,
                                  list_append_history, register_history,
                                  txn_anomaly_history)
from comdb2_tpu.shrink import (SeedVerdictError, Shrinker, TxnShrinker,
                               atoms_of, check_candidates, minimize)

F = 64   # small frontier: every test shape fits, programs stay tiny


def _host_valid(ops, model="cas-register"):
    return linear.analysis(MODELS[model](), list(ops),
                           backend="host").valid


def _sig(op):
    return (op.process, op.type, op.f, op.value)


# --- atoms & masks -----------------------------------------------------------

def test_atoms_pair_closed_and_info_pinned():
    h = register_history(random.Random(0), 4, 60, p_info=0.2)
    p = pack_history(list(h))
    atoms, pinned = atoms_of(p)
    t = np.asarray(p.type)
    pair = np.asarray(p.pair)
    covered = np.zeros(len(p), bool)
    for a in atoms:
        covered[a] = True
        if len(a) == 2:            # completed pair: mutual partners
            assert pair[a[0]] == a[1] and pair[a[1]] == a[0]
        else:                      # pending invoke: no completion
            assert pair[a[0]] == -1
    # every row is exactly one of: pinned or covered by one atom
    assert not np.any(covered & pinned)
    assert np.all(covered | pinned)
    # :info rows (and their crashed invokes) are pinned, never atoms
    assert np.all(pinned[t == O.INFO])


def test_subset_packed_matches_fresh_pack():
    h = register_history(random.Random(1), 3, 40, p_info=0.1)
    p = pack_history(list(h))
    atoms, pinned = atoms_of(p)
    keep = pinned.copy()
    for a in atoms[::2]:           # drop every other pair
        keep[a] = True
    sub = subset_packed(p, keep)
    fresh = pack_history([op.with_() for op in sub.ops])
    # ids differ (shared vs fresh tables) — compare semantically
    assert [_sig(a) for a in sub.ops] == [_sig(b) for b in fresh.ops]
    assert _host_valid(sub.ops) == _host_valid(fresh.ops)


def test_subset_packed_rejects_half_pairs():
    h = [O.invoke(0, "write", 1), O.ok(0, "write", 1)]
    p = pack_history(h)
    with pytest.raises(ValueError, match="pair-closed"):
        subset_packed(p, np.array([True, False]))


def test_check_candidates_batches_and_verdicts():
    base = register_history(random.Random(2), 3, 30, fs=("write",),
                            p_info=0.0)
    h, _ = inject_anomaly(base, "stale-read")
    job = Shrinker(h, "cas-register", F=F)
    full = job.mask_of(job.cur)
    none = job.mask_of([])
    counters = {}
    st = check_candidates(job.packed, [full, none, full], job.memo,
                          F=F, counters=counters)
    assert st[0] == LJ.INVALID and st[2] == LJ.INVALID
    assert st[1] == LJ.VALID          # pinned-only: trivially valid
    # the two live candidates shared ONE dispatch (same pow2 bucket)
    assert counters["dispatches"] == 1
    assert counters["candidates"] == 3


# --- 1-minimality & exact recovery -------------------------------------------

def test_one_minimality_against_host_oracle():
    rng = random.Random(5)
    from comdb2_tpu.ops.synth import mutate

    h = None
    for seed in range(20):
        cand = mutate(rng, register_history(random.Random(seed), 3, 36,
                                            p_info=0.0))
        if _host_valid(cand) is False:
            h = cand
            break
    assert h is not None, "no invalid mutation found"
    r = minimize(h, checker="linear", model="cas-register", F=F)
    assert r.one_minimal and not r.partial and r.valid is False
    assert _host_valid(r.ops) is False
    # the certificate, re-derived on the HOST engine: removing any
    # remaining pair yields VALID/UNKNOWN
    p = pack_history([op.with_() for op in r.ops])
    atoms, pinned = atoms_of(p)
    assert atoms, "minimal history has no droppable atoms?"
    for k in range(len(atoms)):
        keep = pinned.copy()
        for j, a in enumerate(atoms):
            if j != k:
                keep[a] = True
        assert _host_valid(subset_packed(p, keep).ops) is not False, \
            f"dropping atom {k} stayed INVALID — not 1-minimal"


@pytest.mark.parametrize("kind", ANOMALY_KINDS)
def test_ground_truth_recovery(kind):
    # bases chosen so the injected minimum is provably unique (see
    # inject_anomaly's docstring): write-free for lost-update,
    # write-only otherwise
    fs = ("read",) if kind == "lost-update" else ("write",)
    base = register_history(random.Random(7), 3, 50, fs=fs,
                            p_info=0.0)
    assert _host_valid(base) is True
    h, truth = inject_anomaly(base, kind)
    r = minimize(h, checker="linear", model="cas-register", F=F)
    assert r.one_minimal and r.valid is False
    assert sorted(map(_sig, r.ops)) == sorted(map(_sig, truth)), kind


def test_round_cap_bounds_candidates_and_still_certifies():
    # the serving tick's bounded mode: no round may test more than
    # round_cap candidates, and the capped greedy sweep still reaches
    # the exact minimum WITH the 1-minimality certificate
    base = register_history(random.Random(41), 3, 30, fs=("read",),
                            p_info=0.0)
    h, truth = inject_anomaly(base, "lost-update")
    job = Shrinker(h, "cas-register", F=F, round_cap=2)
    seen = 0
    while not job.step():
        assert job.counters["candidates"] - seen <= 2
        seen = job.counters["candidates"]
    assert job.error is None
    r = job.result()
    assert r.one_minimal
    assert sorted(map(_sig, r.ops)) == sorted(map(_sig, truth))


# --- seed rejection ----------------------------------------------------------

def test_valid_seed_rejected():
    h = register_history(random.Random(9), 3, 24, p_info=0.0)
    with pytest.raises(SeedVerdictError) as ei:
        minimize(h, checker="linear", model="cas-register", F=F)
    assert ei.value.verdict is True


def test_unknown_seed_rejected_not_looped():
    # 5 concurrent pending writes: the frontier after the first ok
    # segment exceeds F=2, so the seed verdict is UNKNOWN — shrink
    # must raise immediately (error, not a loop)
    h = [O.invoke(i, "write", i) for i in range(5)]
    h += [O.ok(i, "write", i) for i in range(5)]
    assert int(check_candidates(
        pack_history(list(h)),
        [np.ones(10, bool)],
        Shrinker(h, "cas-register", F=2).memo, F=2)[0]) == LJ.UNKNOWN
    with pytest.raises(SeedVerdictError) as ei:
        minimize(h, checker="linear", model="cas-register", F=2)
    assert ei.value.verdict == "unknown"


# --- txn axis ----------------------------------------------------------------

def _shift(ops, dp=100, dk=100):
    out = []
    for op in ops:
        v = op.value
        if v is not None:
            v = tuple((f, k + dk, x) for f, k, x in v)
        out.append(op.with_(process=op.process + dp, value=v))
    return out


def test_txn_minimal_cycle_vs_host_scc_oracle():
    from comdb2_tpu.txn.scc import cyclic_layers_host

    clean = list_append_history(random.Random(11), 3, 24, 3)
    h = list(clean) + _shift(txn_anomaly_history("g2-item"))
    r = minimize(h, checker="txn")
    assert r.one_minimal and r.valid is False
    assert r.extra["anomaly_class"] == "G2-item"
    kept = r.extra["txns"]
    g = TxnShrinker(h).graph
    idx = np.asarray(kept, np.int64)
    sub = g.adj[:, idx[:, None], idx[None, :]]
    # the kept set IS cyclic per the host oracle...
    assert cyclic_layers_host(sub, realtime=False).any()
    # ...and 1-minimal: removing any txn leaves it acyclic
    for drop in range(len(kept)):
        rest = np.asarray([t for j, t in enumerate(kept) if j != drop],
                          np.int64)
        sub2 = g.adj[:, rest[:, None], rest[None, :]]
        assert not cyclic_layers_host(sub2, realtime=False).any()
    # the write-skew cycle lives entirely in the injected fixture
    assert len(kept) == 2
    assert all(g.txns[t].op.process >= 100 for t in kept)
    # the emitted ops include the EVIDENCE reader (the audit read
    # that recovered the version orders — not on the cycle), so the
    # minimal history re-checks INVALID standalone
    from comdb2_tpu.txn import check_txn
    assert r.extra.get("evidence_txns"), r.extra
    assert check_txn(r.ops, backend="host")["valid?"] is False


def test_txn_direct_anomaly_seed_answers_immediately():
    r = minimize(_shift(txn_anomaly_history("g1a")), checker="txn")
    assert r.valid is False and not r.one_minimal
    assert "direct-anomaly" in r.extra["note"]
    assert r.extra["anomalies"] == ["G1a"]


def test_txn_valid_seed_rejected():
    clean = list_append_history(random.Random(13), 3, 16, 3)
    with pytest.raises(SeedVerdictError) as ei:
        minimize(clean, checker="txn")
    assert ei.value.verdict is True


# --- service kind ------------------------------------------------------------

def _drain(core, deadline_s=120.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        done = core.tick(time.monotonic())
        if done:
            return done
    raise AssertionError("service shrink never completed")


def test_service_shrink_roundtrip():
    from comdb2_tpu.ops.native_loader import parse_history_fast
    from comdb2_tpu.service import VerifierCore

    core = VerifierCore(F=F, batch_cap=8)
    base = register_history(random.Random(17), 3, 36, fs=("write",),
                            p_info=0.0)
    h, truth = inject_anomaly(base, "stale-read")
    pend, reply = core.submit(
        {"op": "check", "kind": "shrink", "id": 1,
         "history": history_to_edn(h)}, time.monotonic())
    assert reply is None and pend is not None
    (_, r), = _drain(core)
    assert r["ok"] and r["valid"] is False and r["kind"] == "shrink"
    assert r["one_minimal"] and not r["partial"]
    assert r["minimal_ops"] == len(truth)
    # the reply's minimal history re-checks INVALID on the host
    minimal = parse_history_fast(r["minimal_history"])
    assert _host_valid(minimal) is False
    assert sorted(map(_sig, minimal)) == sorted(map(_sig, truth))
    st = core.status()
    assert st["shrink_requests"] == 1 and st["shrink_rounds"] >= 1


def test_service_shrink_deadline_returns_partial():
    from comdb2_tpu.service import VerifierCore

    core = VerifierCore(F=F, batch_cap=8)
    base = register_history(random.Random(19), 3, 40, fs=("write",),
                            p_info=0.0)
    h, _ = inject_anomaly(base, "stale-read")
    t0 = time.monotonic()
    pend, reply = core.submit(
        {"op": "check", "kind": "shrink", "id": 2,
         "history": history_to_edn(h), "deadline_ms": 3_600_000}, t0)
    assert reply is None
    assert core.tick(t0) == []          # round 1 (seed): still going
    done = core.tick(t0 + 3601)         # long past the deadline
    (_, r), = done
    assert r["ok"] and r["partial"] is True and r["cause"] == "deadline"
    assert r["valid"] is False          # seed WAS verified invalid
    assert not r["one_minimal"]         # certificate never ran
    assert r["minimal_ops"] <= r["seed_ops"]


def test_service_shrink_bad_seed_is_bad_request():
    from comdb2_tpu.service import VerifierCore

    core = VerifierCore(F=F, batch_cap=8)
    good = register_history(random.Random(23), 3, 24, p_info=0.0)
    pend, reply = core.submit(
        {"op": "check", "kind": "shrink", "id": 3,
         "history": history_to_edn(good)}, time.monotonic())
    assert reply is None
    (_, r), = _drain(core)
    assert r["ok"] is False and r["error"] == "bad-request"
    assert "seed verdict" in r["message"]


def test_service_shrink_txn_kind():
    from comdb2_tpu.service import VerifierCore

    core = VerifierCore(F=F, batch_cap=8)
    clean = list_append_history(random.Random(29), 3, 16, 3)
    h = list(clean) + _shift(txn_anomaly_history("g2-item"))
    pend, reply = core.submit(
        {"op": "check", "kind": "shrink", "txn": True, "id": 4,
         "history": history_to_edn(h)}, time.monotonic())
    assert reply is None
    (_, r), = _drain(core)
    assert r["ok"] and r["valid"] is False
    assert r["anomaly_class"] == "G2-item" and r["one_minimal"]


# --- filetest + store artifacts ----------------------------------------------

def test_filetest_shrink_store_artifacts(tmp_path):
    from comdb2_tpu import filetest
    from comdb2_tpu.ops.native_loader import parse_history_fast

    base = register_history(random.Random(31), 3, 40, fs=("write",),
                            p_info=0.0)
    h, truth = inject_anomaly(base, "stale-read")
    hist = tmp_path / "hist.edn"
    hist.write_text(history_to_edn(h))
    store = tmp_path / "store"
    rc = filetest.main(["--shrink", "--store", str(store), str(hist)])
    assert rc == 1                      # the seed verdict's exit code
    runs = [d for d in os.listdir(store / "shrink") if d != "latest"]
    assert len(runs) == 1
    run = store / "shrink" / runs[0]
    minimal = parse_history_fast((run / "minimal.edn").read_text())
    assert sorted(map(_sig, minimal)) == sorted(map(_sig, truth))
    assert (run / "shrink.svg").exists()
    results = (run / "results.edn").read_text()
    assert '"one-minimal?" true' in results
    assert '"reverified-valid?" false' in results
    # the run is linked from the store index like any harness run
    from comdb2_tpu.harness.web import _runs
    assert any(name == "shrink" for name, _, _ in _runs(str(store)))


def test_filetest_shrink_rejects_valid_seed(tmp_path, capsys):
    from comdb2_tpu import filetest

    good = register_history(random.Random(37), 3, 20, p_info=0.0)
    hist = tmp_path / "good.edn"
    hist.write_text(history_to_edn(good))
    rc = filetest.main(["--shrink", "--store",
                        str(tmp_path / "store"), str(hist)])
    assert rc == 0                      # verdict exit code unchanged
    assert "only INVALID histories shrink" in capsys.readouterr().err
    assert not (tmp_path / "store").exists()
