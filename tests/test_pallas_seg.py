"""Fused Pallas segment engine: availability gating + CPU fallback.

The kernel itself only lowers on TPU (Mosaic); these CPU-mesh tests
check the graceful-degradation contract — spec gating, fallback in the
driver — and the host-side packing helpers. The TPU correctness fuzz
(vs the XLA engine, 120 seeds incl. mutated histories) lives in
``scripts/fuzz_pallas_seg.py`` and is exercised on real hardware.
"""

import numpy as np
import pytest

from comdb2_tpu.checker import pallas_seg as PS
from comdb2_tpu.checker import linear_jax as LJ
from comdb2_tpu.checker import analysis
from comdb2_tpu.models import model as M
from comdb2_tpu.models.memo import memo as make_memo
from comdb2_tpu.ops import op as O
from comdb2_tpu.ops.packed import pack_history


def test_spec_gating():
    s = PS.spec_for(8, 32, 7, 4)
    assert s is not None and s.table_rows == 2
    assert PS.spec_for(8, 32, 8, 4) is None          # P > 7
    assert PS.spec_for(64, 64, 2, 4) is None         # table > 1024
    assert PS.spec_for(2, 2, 1, 9) is None           # K > 8
    # key budget: huge transition space overflows the two words
    assert PS.spec_for(8, 1 << 28, 2, 4) is None


def test_spec_chunk_shrinks_with_k():
    wide = PS.spec_for(4, 4, 2, 8)
    narrow = PS.spec_for(4, 4, 2, 2)
    assert wide is not None and narrow is not None
    assert wide.chunk <= narrow.chunk
    assert wide.chunk * (2 + 2 * wide.K) <= 14336


def test_pack_segments_pads_dead():
    h = [O.invoke(0, "write", 1), O.ok(0, "write", 1)]
    packed = pack_history(h)
    segs = LJ.make_segments(packed)
    spec = PS.spec_for(4, 4, 1, segs.inv_proc.shape[1])
    chunks = PS.pack_segments(segs, spec)
    assert chunks.shape[0] == 1
    flat = chunks.reshape(-1, 2 + 2 * spec.K)
    assert (flat[1:, 0] == -1).all()        # padding segments dead
    assert flat[0, 0] == 0                  # the real ok


def test_initial_frontier_layout():
    spec = PS.spec_for(4, 4, 3, 2)
    hi, lo = PS.initial_frontier(spec)
    assert hi.shape == (PS.ROWS, PS.LANES)
    # exactly one valid lane
    assert int((hi < PS.SENT_HI).sum()) == 1
    # every slot field of the root config reads IDLE (1)
    for q in range(spec.P):
        w, sh = spec.slot_pos[q]
        word = hi[0, 0] if w else lo[0, 0]
        assert (int(word) >> sh) & ((1 << spec.slot_bits) - 1) == 1


def test_driver_falls_back_without_mosaic():
    """On the CPU mesh the kernel can't lower; analysis() must still
    produce the right verdicts through the XLA engines."""
    h = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
         O.invoke(1, "read", None), O.ok(1, "read", 1)] * 40
    a = analysis(M.register(), h, backend="device")
    assert a.valid is True
    assert a.info.get("engine") != "pallas-fused"


def test_check_device_pallas_none_when_unfit():
    h = [O.invoke(0, "write", 1), O.ok(0, "write", 1)]
    packed = pack_history(h)
    mm = make_memo(M.register(), packed)
    segs = LJ.make_segments(packed)
    r = PS.check_device_pallas(mm.succ, segs, n_states=64,
                               n_transitions=64, P=2)
    assert r is None                        # table too large: no fit
