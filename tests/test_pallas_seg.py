"""Fused Pallas segment engine: availability gating + CPU fallback.

The kernel itself only lowers on TPU (Mosaic); these CPU-mesh tests
check the graceful-degradation contract — spec gating, fallback in the
driver — and the host-side packing helpers. The TPU correctness fuzz
(vs the XLA engine, 120 seeds incl. mutated histories) lives in
``scripts/fuzz_pallas_seg.py`` and is exercised on real hardware.
"""

import numpy as np
import pytest

from comdb2_tpu.checker import pallas_seg as PS
from comdb2_tpu.checker import linear_jax as LJ
from comdb2_tpu.checker import analysis
from comdb2_tpu.models import model as M
from comdb2_tpu.models.memo import memo as make_memo
from comdb2_tpu.ops import op as O
from comdb2_tpu.ops.packed import pack_history


def test_spec_gating():
    s = PS.spec_for(8, 32, 7, 4)
    assert s is not None and s.table_rows == 2
    assert s.table_rows_pad == 8
    assert s.rows == 8 and s.n_words == 2
    big = PS.spec_for(64, 64, 2, 4)                  # 4096-entry table
    assert big is not None and big.table_rows_pad == 32
    # P in 8..15: the (16,128) tier, up to 3 key words
    wide = PS.spec_for(8, 32, 10, 4)
    assert wide is not None and wide.rows == 16
    assert wide.n_words == 3                         # 10*6+3 = 63 bits
    assert PS.spec_for(8, 32, 16, 4) is None         # P > 15
    huge = PS.spec_for(128, 64, 2, 4)                # 8192-entry table
    assert huge is not None and huge.table_rows_pad == 64
    assert PS.spec_for(256, 64, 2, 4) is None        # table > 8192
    assert PS.spec_for(2, 2, 1, 9) is None  # analysis: ignore[pallas-k-cap]
    # key budget: 15 slots x 13 bits = 8 words > 3 — rejected by the
    # word-layout loop itself (table 2*4096 = 8192 entries fits, so
    # this genuinely exercises the n_words cap, not MAX_TABLE)
    assert PS.spec_for(2, 4094, 15, 4) is None
    assert PS.spec_for(8, 1 << 27, 1, 4) is None     # table too big
    # field positions never straddle a word and respect the budget
    for spec in (s, wide):
        for (w, sh), bits in ([(spec.state_pos, spec.state_bits)]
                              + [(p, spec.slot_bits)
                                 for p in spec.slot_pos]):
            assert w < spec.n_words and sh + bits <= 31


def test_spec_chunk_shrinks_with_k():
    wide = PS.spec_for(4, 4, 2, 8)
    narrow = PS.spec_for(4, 4, 2, 2)
    assert wide is not None and narrow is not None
    assert wide.chunk <= narrow.chunk
    assert wide.chunk * (2 + 2 * wide.K) <= 14336


def test_pack_segments_pads_dead():
    h = [O.invoke(0, "write", 1), O.ok(0, "write", 1)]
    packed = pack_history(h)
    segs = LJ.make_segments(packed)
    spec = PS.spec_for(4, 4, 1, segs.inv_proc.shape[1])
    chunks = PS.pack_segments(segs, spec)
    assert chunks.shape[0] == 1
    flat = chunks.reshape(-1, 2 + 2 * spec.K)
    assert (flat[1:, 0] == -1).all()        # padding segments dead
    assert flat[0, 0] == 0                  # the real ok


def test_initial_frontier_layout():
    for P in (3, 10):
        spec = PS.spec_for(4, 4, P, 2)
        ws = PS.initial_frontier(spec)
        assert len(ws) == spec.n_words
        assert ws[0].shape == (spec.rows, PS.LANES)
        # exactly one valid lane (the top word carries the sentinel)
        assert int((ws[-1] < PS.SENT_HI).sum()) == 1
        # every slot field of the root config reads IDLE (1)
        for q in range(spec.P):
            w, sh = spec.slot_pos[q]
            word = int(ws[w][0, 0])
            assert (word >> sh) & ((1 << spec.slot_bits) - 1) == 1


def test_driver_falls_back_without_mosaic():
    """On the CPU mesh the kernel can't lower; analysis() must still
    produce the right verdicts through the XLA engines."""
    h = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
         O.invoke(1, "read", None), O.ok(1, "read", 1)] * 40
    a = analysis(M.register(), h, backend="device")
    assert a.valid is True
    assert a.info.get("engine") != "pallas-fused"


def test_pack_stream_layout():
    """Stream = [R][h0][R][h1]...[R]; starts index each history's
    first segment; everything after the trailing R is dead padding."""
    h0 = [O.invoke(0, "write", 1), O.ok(0, "write", 1)]
    h1 = [O.invoke(0, "write", 2), O.ok(0, "write", 2),
          O.invoke(0, "read", None), O.ok(0, "read", 2)]
    segs = [LJ.make_segments(pack_history(h)) for h in (h0, h1)]
    spec = PS.spec_for(4, 8, 1, 2)
    chunks, starts = PS.pack_stream(segs, spec)
    flat = chunks.reshape(-1, 2 + 2 * spec.K)
    S0 = segs[0].ok_proc.shape[0]
    S1 = segs[1].ok_proc.shape[0]
    assert flat[0, 0] == PS.RESET
    assert starts[0] == 1 and starts[1] == 2 + S0
    assert flat[1 + S0, 0] == PS.RESET          # boundary marker
    trailing = 2 + S0 + S1
    assert flat[trailing, 0] == PS.RESET
    assert (flat[trailing + 1:, 0] == -1).all()


def test_check_batch_stream_engine_falls_back_on_cpu():
    """engine='auto' must not pick the stream engine where Mosaic is
    unavailable; an explicit engine='stream' request must still produce
    correct verdicts through the fallback."""
    import random

    import histgen
    from comdb2_tpu.checker.batch import pack_batch, check_batch

    rng = random.Random(5)
    hs = [histgen.register_history(rng, n_procs=2, n_events=12,
                                   p_info=0.0) for _ in range(6)]
    batch = pack_batch(hs, M.cas_register())
    st, fa, n = check_batch(batch, engine="stream")
    st2, fa2, n2 = check_batch(batch, engine="keys")
    assert (st == st2).all() and (n == n2).all()
    # auto must not pick the stream engine here (no Mosaic): same
    # verdicts via the XLA ladder
    st3, _, n3 = check_batch(batch)
    assert (st3 == st2).all() and (n3 == n2).all()


def test_check_batch_stream_unknown_escalates(monkeypatch):
    """Kernel UNKNOWNs (its frontier is fixed at 128) must be re-run
    through the XLA engines at the caller's requested F, not surfaced
    as spurious unknowns."""
    import random

    import histgen
    from comdb2_tpu.checker import batch as B

    rng = random.Random(6)
    hs = [histgen.register_history(rng, n_procs=2, n_events=16,
                                   p_info=0.0) for _ in range(5)]
    batch = B.pack_batch(hs, M.cas_register())
    want = B.check_batch(batch, engine="keys")

    def fake_dispatch(succ, segs_list, spec, n_states, n_transitions,
                      device=None):
        # history 2 pretends to overflow the kernel frontier (one
        # pipeline slice: slice-local indices are batch indices)
        import numpy as np

        res = np.array([[2, 0, 0] if b == 2 else [0, -1, 1]
                        for b in range(len(segs_list))], np.int32)
        return res, np.zeros(len(segs_list), np.int64)

    monkeypatch.setattr(B.PSEG, "available", lambda: True)
    monkeypatch.setattr(B.PSEG, "stream_dispatch", fake_dispatch)
    st, fa, n = B.check_batch(batch, F=256, engine="stream")
    assert (st == want[0]).all()          # UNKNOWN replaced by verdict
    assert n[2] == want[2][2]             # escalated lane's real count


def test_check_device_pallas_none_when_unfit():
    h = [O.invoke(0, "write", 1), O.ok(0, "write", 1)]
    packed = pack_history(h)
    mm = make_memo(M.register(), packed)
    segs = LJ.make_segments(packed)
    r = PS.check_device_pallas(mm.succ, segs, n_states=256,
                               n_transitions=64, P=2)
    assert r is None                        # table too large: no fit


# --- interpret mode: the PRODUCTION kernel's semantics on CPU ---------------
#
# Mosaic is TPU-only, but Pallas interpret mode executes the kernel's
# exact traced body as plain XLA ops — so the CPU suite can assert the
# kernel agrees bit-for-bit with the XLA engines, including on the
# sharded stream path (round-3 VERDICT #3: before this, the kernel's
# semantics ran nowhere but single-chip TPU). One module-scoped history
# set keeps interpret compiles (~tens of seconds each) to a minimum.

@pytest.fixture()
def interpret_kernel():
    PS.use_interpret(True)
    yield
    PS.use_interpret(False)


def _parity_histories():
    import random

    import histgen

    rng = random.Random(909)
    hs = [histgen.register_history(rng, n_procs=4, n_events=40,
                                   values=3, p_info=0.0)
          for _ in range(4)]
    # one invalid variant so the fail path is compared too
    hs.append(histgen.mutate(rng, hs[0]))
    return hs


def test_interpret_kernel_matches_xla_single(interpret_kernel):
    from comdb2_tpu.models.memo import memo as make_memo

    assert PS.interpret_active()
    assert PS.available()
    for h in _parity_histories():
        packed = pack_history(h)
        mm = make_memo(M.cas_register(), packed)
        segs = LJ.make_segments(packed)
        P = len(packed.process_table)
        r = PS.check_device_pallas(mm.succ, segs, n_states=mm.n_states,
                                   n_transitions=mm.n_transitions, P=P)
        assert r is not None
        succ = LJ.pad_succ(mm.succ, 16, 16)
        st, fs, n = LJ.check_device_seg2(
            succ, segs.inv_proc, segs.inv_tr, segs.ok_proc, segs.depth,
            F=PS.F, Fs=32, P=P + (P & 1), n_states=mm.n_states,
            n_transitions=mm.n_transitions)
        assert r == (int(st), int(fs), int(n))


def test_interpret_kernel_stream_sharded_matches_keys(interpret_kernel):
    """The sharded stream path (slices spread across the 8-device CPU
    mesh) through the interpret kernel, vs the keys engine."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from comdb2_tpu.checker.batch import check_batch, pack_batch

    hs = _parity_histories() * 2                # 10 histories
    batch = pack_batch(hs, M.cas_register())
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("batch",))
    info_s: dict = {}
    st_s, fa_s, n_s = check_batch(batch, F=PS.F, mesh=mesh,
                                  engine="stream", info=info_s)
    assert info_s["engine"] == "stream-sharded"
    info_k: dict = {}
    st_k, fa_k, n_k = check_batch(batch, F=PS.F, mesh=mesh,
                                  engine="keys", info=info_k)
    assert info_k["engine"] == "keys-sharded"
    np.testing.assert_array_equal(st_s, st_k)
    np.testing.assert_array_equal(fa_s, fa_k)
    # n is only defined on VALID verdicts (on INVALID the kernel
    # reports the emptied frontier, the keys engine the pre-failure
    # count — same contract as UNKNOWN in CLAUDE.md)
    ok = st_s == LJ.VALID
    np.testing.assert_array_equal(n_s[ok], n_k[ok])


def test_interpret_kernel_wide_p10(interpret_kernel):
    """The (16,128)/3-word tier (P in 8..15 — round-3 VERDICT #2, the
    reference register test's concurrency 10): kernel verdicts must
    match the XLA seg engine on valid AND invalid histories."""
    import random

    import histgen
    from comdb2_tpu.models.memo import memo as make_memo

    rng = random.Random(777)
    base = histgen.register_history(rng, n_procs=10, n_events=60,
                                    values=3, p_info=0.0,
                                    max_pending=4)
    for h in (base, histgen.mutate(rng, base)):
        packed = pack_history(h)
        P = len(packed.process_table)
        assert P == 10
        mm = make_memo(M.cas_register(), packed)
        segs = LJ.make_segments(packed)
        spec = PS.spec_for(mm.n_states, mm.n_transitions, P,
                           segs.inv_proc.shape[1])
        assert spec is not None and spec.rows == 16
        r = PS.check_device_pallas(mm.succ, segs, n_states=mm.n_states,
                                   n_transitions=mm.n_transitions, P=P)
        assert r is not None
        succ = LJ.pad_succ(mm.succ, 16, 32)
        st, fs, n = LJ.check_device_seg2(
            succ, segs.inv_proc, segs.inv_tr, segs.ok_proc, segs.depth,
            F=PS.F, Fs=32, P=P, n_states=mm.n_states,
            n_transitions=mm.n_transitions)
        assert (r[0], r[1]) == (int(st), int(fs)), (r, int(st), int(fs))
        if r[0] == LJ.VALID:
            assert r[2] == int(n)


def test_interpret_stream_renamed_slots_matches_host(interpret_kernel):
    """The streamed kernel over slot-RENAMED segments (the production
    batch path since round 5) must agree with the host engine —
    verdicts, fail indices, and (on VALID) counts. (Replaces the
    row-parallel tier parity test: that tier measured strictly slower
    at every real shape and was removed — round-4 VERDICT Weak #7.)"""
    import random

    import histgen
    from comdb2_tpu.checker import linear_host
    from comdb2_tpu.checker.batch import pack_batch, _stream_segments
    from comdb2_tpu.ops.packed import pack_history

    rng = random.Random(31)
    hs = []
    for i in range(20):
        h = histgen.register_history(rng, n_procs=rng.randint(2, 9),
                                     n_events=rng.randint(8, 40),
                                     values=3, p_info=0.0,
                                     max_pending=3)
        if i % 4 == 1:
            h = h + [O.invoke(90, "read", None), O.ok(90, "read", 9)]
        hs.append(h)
    batch = pack_batch(hs, M.cas_register())
    segs_list, P_stream = _stream_segments(batch)
    assert P_stream <= 4          # renaming collapsed 9-proc histories
    sizes = dict(n_states=batch.memo.n_states,
                 n_transitions=batch.memo.n_transitions)
    got = PS.check_device_pallas_stream(
        batch.memo.succ, segs_list, P=P_stream, **sizes)
    assert got is not None
    from comdb2_tpu.models.memo import memo as make_memo
    for i, (h, g) in enumerate(zip(hs, got)):
        packed = pack_history(list(h))
        hr = linear_host.check(make_memo(M.cas_register(), packed),
                               packed, max_configs=1 << 16)
        assert (g[0] == LJ.VALID) == hr.valid, (g, hr.valid)
        if g[0] == LJ.VALID:
            assert g[2] == hr.final_count, (g, hr)
        else:
            assert int(segs_list[i].seg_index[g[1]]) == hr.op_index


def test_interpret_lazy_compaction_scattered_frontier(interpret_kernel):
    """The round-5 lazy-compaction path, DETERMINISTICALLY: a frontier
    that grows past the mini window M (full tier), gets filtered down
    to a mini-sized SCATTERED set by ok filters, and must then be
    compacted at closure entry for the mini tier to read it. Five
    concurrent distinct writes give an 81-config closure at the first
    ok (M = 128//(P+1) = 18 at P=6), shrinking through the remaining
    oks — verdict and final count must match the host engine exactly.
    A wrong entry-compaction cond (e.g. stale count, >= vs >) breaks
    the count or flips the verdict here, not just on lucky fuzz seeds.
    """
    from comdb2_tpu.checker import linear_host
    from comdb2_tpu.models.memo import memo as make_memo

    h = []
    k = 5
    for p in range(k):
        h.append(O.invoke(p, "write", p))
    for p in range(k):
        h.append(O.ok(p, "write", p))
    # a tail of small segments AFTER the shrink: these are the
    # segments that enter with a mini-sized scattered frontier
    for i in range(6):
        p = i % 2
        h.append(O.invoke(p, "write", i % k))
        h.append(O.ok(p, "write", i % k))
    packed = pack_history(h)
    mm = make_memo(M.cas_register(), packed)
    segs = LJ.make_segments(packed, s_pad=16, k_pad=8)
    P = len(packed.process_table)
    # structural precondition: the history really exercises the path —
    # host per-segment frontier must cross above M then return <= M
    hr = linear_host.check(mm, packed, max_configs=1 << 16)
    assert hr.valid
    M_mini = 128 // (P + 1)
    assert hr.max_frontier > M_mini, (hr.max_frontier, M_mini)
    succ = LJ.pad_succ(mm.succ, 8, 8)
    r = PS.check_device_pallas(succ, segs, n_states=8,
                               n_transitions=8, P=P)
    assert r is not None
    assert r[0] == LJ.VALID, r
    assert r[2] == hr.final_count, (r, hr.final_count)
