"""The txn serializability checker: edge inference, host/device
engine parity, Adya classification, counterexample decode, the
list-append generator + MemDB client, the checker-protocol wrapper,
merge_valid coercion, adapters, and filetest --txn."""

import random
import subprocess
import sys

import numpy as np
import pytest

from comdb2_tpu.checker.checkers import (Serializable, compose,
                                         merge_valid, UNKNOWN)
from comdb2_tpu.ops import op as O
from comdb2_tpu.ops.history import history_to_edn, parse_history
from comdb2_tpu.ops.synth import (list_append_history,
                                  txn_anomaly_history)
from comdb2_tpu.txn import check_txn, infer_edges
from comdb2_tpu.txn.closure_jax import cyclic_layers_device
from comdb2_tpu.txn.counterexample import decode, render_text
from comdb2_tpu.txn.edges import PLANES, TXN_N_FLOOR
from comdb2_tpu.txn.scc import cyclic_layers_host


def _txn(p, mops, typ="ok"):
    inv = tuple((f, k, None if f == "r" else v) for f, k, v in mops)
    return [O.invoke(p, "txn", inv), O.Op(p, typ, "txn", tuple(mops))]


# --- edge inference ----------------------------------------------------------

def test_edges_ww_wr_rw():
    h = (_txn(0, [("append", "x", 1)])
         + _txn(1, [("r", "x", (1,)), ("append", "x", 2)])
         + _txn(2, [("r", "x", (1, 2))]))
    g = infer_edges(h)
    assert g.n == 3
    ww, wr, rw, rt = (g.adj[i] for i in range(4))
    assert ww[0, 1] and not ww[1, 0]        # version order x: 1 then 2
    assert wr[0, 1] and wr[1, 2]            # each read's last element
    assert not rw.any() and not rt.any()    # reads saw full prefixes
    assert g.orders["x"] == (1, 2)


def test_edges_rw_from_empty_and_stale_reads():
    h = (_txn(0, [("r", "x", ())])          # missed everything
         + _txn(1, [("append", "x", 1)])
         + _txn(2, [("r", "x", (1,))]))
    g = infer_edges(h)
    rw = g.adj[PLANES.index("rw")]
    assert rw[0, 1]                          # empty read -> first writer
    assert g.adj[PLANES.index("wr")][1, 2]


def test_edges_own_append_not_a_dependency():
    # a txn reading back its own append must not self-depend
    h = _txn(0, [("append", "x", 1), ("r", "x", (1,))]) \
        + _txn(1, [("r", "x", (1,))])
    g = infer_edges(h)
    assert not g.adj[:, 0, 0].any()
    assert g.adj[PLANES.index("wr")][0, 1]


def test_edges_realtime_optional():
    h = _txn(0, [("append", "x", 1)]) + _txn(1, [("r", "x", (1,))])
    assert not infer_edges(h).adj[PLANES.index("rt")].any()
    g = infer_edges(h, realtime=True)
    assert g.adj[PLANES.index("rt")][0, 1]


def test_failed_txn_excluded_unless_observed():
    h = _txn(0, [("append", "x", 1)], typ="fail") \
        + _txn(1, [("r", "x", ())])
    g = infer_edges(h)
    assert g.n == 1                          # the fail txn never ran
    assert not [a for a in g.anomalies if a["name"] == "G1a"]
    # ... but once OBSERVED it joins the graph and flags G1a
    h = _txn(0, [("append", "x", 1)], typ="fail") \
        + _txn(1, [("r", "x", (1,))])
    g = infer_edges(h)
    assert g.n == 2 and g.txns[0].dirty
    assert [a for a in g.anomalies if a["name"] == "G1a"]


def test_incompatible_order_flagged():
    h = (_txn(0, [("append", "x", 1)]) + _txn(1, [("append", "x", 2)])
         + _txn(2, [("r", "x", (1, 2))]) + _txn(3, [("r", "x", (2, 1))]))
    r = check_txn(h, backend="host")
    assert r["valid?"] is False
    assert any(a["name"] == "incompatible-order" for a in r["anomalies"])


def test_padded_bucketing():
    g = infer_edges(txn_anomaly_history("g2-item"))
    p = g.padded()
    assert p.shape == (4, TXN_N_FLOOR, TXN_N_FLOOR)
    assert p[:, g.n:, :].sum() == 0 and p[:, :, g.n:].sum() == 0
    with pytest.raises(ValueError):
        g.padded(2)


# --- classification + counterexample -----------------------------------------

@pytest.mark.parametrize("kind,cls", [
    ("g0", "G0"), ("g1c", "G1c"), ("g2-item", "G2-item")])
def test_anomaly_classification(kind, cls):
    for backend in ("host", "device"):
        r = check_txn(txn_anomaly_history(kind), backend=backend)
        assert r["valid?"] is False
        assert r["counterexample"]["class"] == cls, (backend, r)


@pytest.mark.parametrize("kind", ["g1a", "duplicate"])
def test_direct_anomalies(kind):
    r = check_txn(txn_anomaly_history(kind), backend="host")
    assert r["valid?"] is False
    assert any(a["name"].lower().startswith(kind[:4])
               for a in r["anomalies"])


def test_clean_history_valid_both_backends():
    for backend in ("host", "device"):
        r = check_txn(txn_anomaly_history("clean"), backend=backend)
        assert r["valid?"] is True, (backend, r)
        assert r["counterexample"] is None


def test_counterexample_speaks_ops():
    r = check_txn(txn_anomaly_history("g2-item"), backend="host")
    cex = r["counterexample"]
    steps = cex["cycle"]
    assert len(steps) == 2
    edge_types = {s["edge"]["type"] for s in steps}
    assert edge_types == {"rw"}
    # every step names a real txn's process and micro-ops
    for s in steps:
        assert s["status"] == "ok"
        assert any(m[0] == "append" for m in s["value"])
    text = render_text(cex)
    assert "G2-item" in text and "--rw" in text


def test_counterexample_svg_renders(tmp_path):
    from comdb2_tpu.report.txn_svg import render_cycle

    r = check_txn(txn_anomaly_history("g1c"), backend="host")
    svg = render_cycle(r["counterexample"],
                       str(tmp_path / "cycle.svg"))
    assert svg.startswith("<svg") and "G1c" in svg
    assert (tmp_path / "cycle.svg").exists()


# --- engine parity -----------------------------------------------------------

def test_host_device_parity_random_graphs():
    rng = random.Random(11)
    for _ in range(20):
        n = rng.choice([5, 9, 16, 31])
        adj = np.zeros((4, n, n), dtype=bool)
        for _e in range(rng.randrange(1, 4 * n)):
            i, j = rng.randrange(n), rng.randrange(n)
            if i != j:
                adj[rng.randrange(4), i, j] = True
        for rt in (False, True):
            dh = cyclic_layers_host(adj, realtime=rt)
            dd = cyclic_layers_device(adj, realtime=rt)
            assert np.array_equal(dh, dd), (n, rt)


def test_parity_on_generated_histories():
    rng = random.Random(5)
    for seed in range(5):
        h = list_append_history(random.Random(seed), n_procs=4,
                                n_txns=30, n_keys=3,
                                p_info=0.1, p_fail=0.15)
        g = infer_edges(h)
        if not g.adj.any():
            continue
        assert np.array_equal(cyclic_layers_host(g.adj),
                              cyclic_layers_device(g.adj)), seed


# --- generator + harness client ----------------------------------------------

def test_generator_serializable_by_construction():
    for seed in range(10):
        h = list_append_history(random.Random(seed), n_procs=4,
                                n_txns=30, n_keys=3,
                                p_info=0.1, p_fail=0.1)
        r = check_txn(h, backend="host")
        assert r["valid?"] is True, (seed, r)
        # strict serializability holds too: apply points sit inside
        # op windows, so the serial order extends realtime
        r = check_txn(h, backend="host", realtime=True)
        assert r["valid?"] is True, (seed, r)


def test_memdb_list_append_harness_run(tmp_path):
    from comdb2_tpu.harness import core, fake
    from comdb2_tpu.harness import generator as G
    from comdb2_tpu.workloads import comdb2 as W
    from comdb2_tpu.workloads.sqlish import MemDB

    t = fake.noop_test()
    t.update({
        "nodes": [], "concurrency": 4, "name": "la-mem",
        "store-root": str(tmp_path / "store"),
        "client": W.ListAppendClient(MemDB().connect),
        "model": None,
        "generator": G.clients(G.time_limit(1.0, G.stagger(
            0.005, W.list_append_gen()))),
        "checker": Serializable(backend="host"),
    })
    res = core.run(t)
    assert res["results"]["valid?"] is True, res["results"]
    assert res["results"]["txn-count"] >= 20


def test_serializable_checker_writes_artifacts(tmp_path):
    t = {"name": "txn-art", "start-time": "t0",
         "store-root": str(tmp_path)}
    res = Serializable(backend="host").check(
        t, None, txn_anomaly_history("g2-item"))
    assert res["valid?"] is False
    base = tmp_path / "txn-art" / "t0"
    assert (base / "serializable.txt").exists()
    assert (base / "serializable.svg").exists()
    assert "G2-item" in (base / "serializable.txt").read_text()


# --- verdict-merge machinery -------------------------------------------------

def test_merge_valid_coerces_unrecognized_to_unknown():
    assert merge_valid([True, "crashed"]) == UNKNOWN
    assert merge_valid([True, None]) == UNKNOWN
    # ... but False still dominates everything
    assert merge_valid([False, "crashed"]) is False
    assert merge_valid(["crashed", False]) is False
    assert merge_valid([True, True]) is True
    assert merge_valid([True, UNKNOWN]) == UNKNOWN


def test_compose_with_serializable():
    both = compose({"graph": Serializable(backend="host")})
    res = both.check({}, None, txn_anomaly_history("g1c"))
    assert res["valid?"] is False
    assert res["graph"]["counterexample"]["class"] == "G1c"


# --- adapters (second opinions) ----------------------------------------------

def test_g2_adapter_agrees_with_g2_checker():
    from comdb2_tpu.checker.workloads import g2_checker
    from comdb2_tpu.txn.adapters import g2_as_txns

    # the dangerous interleaving: both inserts commit on key 7
    bad = [
        O.invoke(0, "insert", (7, (1, None))),
        O.ok(0, "insert", (7, (1, None))),
        O.invoke(1, "insert", (7, (None, 2))),
        O.ok(1, "insert", (7, (None, 2))),
    ]
    # the healthy one: the second insert failed validation
    good = [op.with_(type="fail") if i == 3 else op
            for i, op in enumerate(bad)]
    for hist, expect in ((bad, False), (good, True)):
        adya = g2_checker.check(None, None, hist)["valid?"]
        graph = check_txn(g2_as_txns(hist), backend="host")["valid?"]
        assert adya is expect and graph is expect, \
            (expect, adya, graph)
    r = check_txn(g2_as_txns(bad), backend="host")
    assert r["counterexample"]["class"] == "G2-item"


def test_dirty_reads_adapter_agrees():
    from comdb2_tpu.checker.workloads import dirty_reads_checker
    from comdb2_tpu.txn.adapters import dirty_reads_as_txns

    bad = [
        O.invoke(0, "write", 7), O.ok(0, "write", 7),
        O.invoke(1, "write", 8), O.fail(1, "write", 8),
        O.invoke(2, "read", None), O.ok(2, "read", (8, 8, 8)),
    ]
    good = [op.with_(value=(7, 7, 7)) if i == 5 else op
            for i, op in enumerate(bad)]
    for hist, expect in ((bad, False), (good, True)):
        dirty = dirty_reads_checker.check(None, None, hist)["valid?"]
        graph = check_txn(dirty_reads_as_txns(hist),
                          backend="host")["valid?"]
        assert dirty is expect and graph is expect, \
            (expect, dirty, graph)
    r = check_txn(dirty_reads_as_txns(bad), backend="host")
    assert any(a["name"] == "G1a" for a in r["anomalies"])


# --- filetest ---------------------------------------------------------------

def test_filetest_txn_round_trip(tmp_path):
    f = tmp_path / "h.edn"
    f.write_text(history_to_edn(txn_anomaly_history("g2-item")))
    r = subprocess.run(
        [sys.executable, "-m", "comdb2_tpu.filetest", "--txn",
         "--backend", "host", str(f)],
        capture_output=True, text=True, timeout=120,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 1, r.stdout + r.stderr
    assert "G2-item" in r.stdout
    f.write_text(history_to_edn(txn_anomaly_history("clean")))
    r = subprocess.run(
        [sys.executable, "-m", "comdb2_tpu.filetest", "--txn",
         "--backend", "host", str(f)],
        capture_output=True, text=True, timeout=120,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr


def test_edn_round_trip_preserves_micro_ops():
    h = txn_anomaly_history("g1c")
    back = parse_history(history_to_edn(h))
    assert check_txn(back, backend="host")["valid?"] is False
    g1, g2 = infer_edges(h), infer_edges(back)
    assert np.array_equal(g1.adj, g2.adj)


def test_unexpected_value_flagged():
    """A read observing a value nobody appended is fabricated data,
    not a clean run (review regression)."""
    h = _txn(0, [("append", "x", 1)]) + _txn(1, [("r", "x", (1, 5))])
    r = check_txn(h, backend="host")
    assert r["valid?"] is False, r
    assert any(a["name"] == "unexpected-value" and a["values"] == [5]
               for a in r["anomalies"]), r


def test_orphan_completion_unconstrained_in_realtime():
    """A completion with no invoke (truncated history) must not
    fabricate rt edges from its own position — its real invoke may
    have overlapped anything (review regression)."""
    h = (_txn(0, [("append", "x", 7)])
         + [O.Op(1, "ok", "txn", (("r", "x", ()),))]   # orphan
         + _txn(2, [("r", "x", (7,))]))
    r = check_txn(h, backend="host", realtime=True)
    assert r["valid?"] is True, r
    g = infer_edges(h, realtime=True)
    rt = g.adj[PLANES.index("rt")]
    orphan = next(i for i, t in enumerate(g.txns) if t.invoke_at < 0)
    assert not rt[:, orphan].any()      # nothing realtime-precedes it
