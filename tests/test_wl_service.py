"""kind:"wl" through the serving plane (ISSUE 20).

The wl families ride the continuous-batching core unchanged: verdict
parity with ``check_wl_batch`` per family and violation twin, one
dispatch per pow2 bucket (family+shape+model slotting), program-hit
accounting, the host-degrade route, bad-request replies, wl stream
sessions fusing same-beat appends into one program, the checkpoint
verb's migration round-trip, and deadline expiries carrying the wl
kind/family with stages tiling latency.
"""

import time

from comdb2_tpu.checker import wl as W
from comdb2_tpu.checker.wl import batch as WLB
from comdb2_tpu.obs import trace as obs
from comdb2_tpu.ops.history import history_to_edn
from comdb2_tpu.ops.op import invoke, ok
from comdb2_tpu.service.core import VerifierCore
from comdb2_tpu.stream import engine as SE


def test_wl_kind_parity_all_families():
    core = VerifierCore(batch_cap=8)
    rid = 0
    cases = (("bank", lambda v: W.bank_batch(7, 3, violation=v),
              (None, "total", "n")),
             ("sets", lambda v: (W.sets_batch(7, 3, violation=v),
                                 None),
              (None, "lost", "phantom")),
             ("dirty", lambda v: (W.dirty_batch(7, 3, violation=v),
                                  None),
              (None, "dirty", "disagree", "malformed")))
    for family, gen, viols in cases:
        for viol in viols:
            hists, m = gen(viol)
            oracle = W.check_wl_batch(hists, family, m)
            pend = []
            for h in hists:
                rid += 1
                p, r = core.submit(
                    {"kind": "wl", "family": family, "id": rid,
                     "history": history_to_edn(list(h)),
                     **({"wl": m} if m else {})}, obs.monotonic())
                assert r is None, r
                pend.append(p)
            done = {pp.rid: rep for pp, rep in core.tick()}
            for p, o in zip(pend, oracle):
                rep = done[p.rid]
                assert rep["ok"] and rep["kind"] == "wl", rep
                assert rep["valid"] == o["valid?"], \
                    (family, viol, rep, o)
                assert rep["family"] == family
                # stages tile the measured wall (expiries included)
                assert abs(sum(rep["stages"].values())
                           - rep["latency_ms"]) < 1.0, rep


def test_wl_batching_one_dispatch_and_program_hits():
    core = VerifierCore(batch_cap=8)
    hists, m = W.bank_batch(19, 6)
    d0, svc0 = WLB.DISPATCHES, core.m["dispatches"]
    for i, h in enumerate(hists):
        p, r = core.submit({"kind": "wl", "family": "bank",
                            "id": i + 1, "wl": m,
                            "history": history_to_edn(list(h))},
                           obs.monotonic())
        assert r is None
    done = core.tick()
    assert len(done) == 6
    assert WLB.DISPATCHES - d0 == 1, "6 requests must share one program"
    assert core.m["dispatches"] - svc0 == 1
    for _p, rep in done:
        assert rep["valid"] is True and rep["batched"] == 6, rep
        assert rep["engine"] == "wl-device"
        assert rep["bucket"].startswith("wl-bank-"), rep

    # same bucket again is a program hit, not a new program
    hists2, _ = W.bank_batch(23, 3)
    hits0 = core.m["program_hits"]
    for i, h in enumerate(hists2):
        core.submit({"kind": "wl", "family": "bank", "id": 100 + i,
                     "wl": m, "history": history_to_edn(list(h))},
                    obs.monotonic())
    core.tick()
    assert core.m["program_hits"] > hits0


def test_wl_model_key_slot_separation():
    """Two bank models must not share a dispatch — the model is a
    static of the verdict, so it is part of the bucket key."""
    core = VerifierCore(batch_cap=8)
    hists, m_a = W.bank_batch(29, 1)
    m_b = {"n": m_a["n"], "total": int(m_a["total"]) + 2}
    for i, mm in enumerate((m_a, m_b)):
        core.submit({"kind": "wl", "family": "bank", "id": i + 1,
                     "wl": mm,
                     "history": history_to_edn(list(hists[0]))},
                    obs.monotonic())
    done = core.tick()
    assert len(done) == 2
    assert all(rep["batched"] == 1 for _p, rep in done)
    # same history, different total: exactly one model calls it wrong
    assert sorted(rep["valid"] for _p, rep in done) == [False, True]


def test_wl_host_degrade_past_ladder():
    core = VerifierCore(batch_cap=8)
    hist = [invoke(0, "write", 1), ok(0, "write", 1),
            ok(1, "read", tuple([1] * (WLB.WL_NODES[-1] + 4)))]
    p, r = core.submit({"kind": "wl", "family": "dirty", "id": 1,
                        "history": history_to_edn(hist)},
                       obs.monotonic())
    assert r is None and p.bucket is None
    hd0 = core.m["host_degraded"]
    done = {pp.rid: rep for pp, rep in core.tick()}
    rep = done[p.rid]
    assert rep["engine"] == "host" and rep.get("degraded") is True
    assert core.m["host_degraded"] == hd0 + 1


def test_wl_bad_requests():
    core = VerifierCore(batch_cap=8)
    for i, (req, want) in enumerate((
            ({"kind": "wl", "family": "nope", "history": "[]"},
             "unknown"),
            ({"kind": "wl", "family": "bank", "history": "[]"},
             "bank"),
            ({"kind": "wl", "family": "sets"}, "missing"),
            ({"kind": "wl", "family": "sets", "history": "[{:type"},
             "unparseable"))):
        p, r = core.submit({**req, "id": i + 1}, obs.monotonic())
        assert p is None and not r["ok"], (req, r)
        assert want in r["message"], (want, r)


def test_wl_stream_sessions_fuse_per_beat():
    core = VerifierCore(batch_cap=8, max_sessions=4)
    hists, m = W.bank_batch(37, 2)
    sids = []
    for i in (1, 2):
        _, r = core.submit({"kind": "stream", "verb": "open",
                            "id": i, "model": "wl-bank", "wl": m},
                           obs.monotonic())
        assert r["ok"] and r["model"] == "wl-bank", r
        sids.append(r["session"])
    # bad wl params reply bad-request without leaking a session
    _, r = core.submit({"kind": "stream", "verb": "open", "id": 9,
                        "model": "wl-bank"}, obs.monotonic())
    assert not r["ok"] and "bad wl params" in r["message"], r
    assert len(core.sessions) == 2

    # two same-shape appends in one beat -> ONE fused program
    d0, mb0 = SE.DISPATCHES, core.m["stream_megabatches"]
    now = obs.monotonic()
    for i, (sid, h) in enumerate(zip(sids, hists)):
        p, r = core.submit({"kind": "stream", "verb": "append",
                            "id": 20 + i, "session": sid,
                            "history": history_to_edn(list(h))}, now)
        assert r is None, r
    done = core.tick()
    assert SE.DISPATCHES - d0 == 1, SE.DISPATCHES - d0
    assert core.m["stream_megabatches"] - mb0 == 1
    oracle = W.check_wl_batch(hists, "bank", m)
    for (_p, rep), o in zip(done, oracle):
        assert rep["valid"] == o["valid?"], (rep, o)
        assert rep["family"] == "bank"
        assert abs(sum(rep["stages"].values())
                   - rep["latency_ms"]) < 1.0

    _, r = core.submit({"kind": "stream", "verb": "poll", "id": 30,
                        "session": sids[0]}, obs.monotonic())
    assert r["valid"] is True and r["family"] == "bank", r
    _, r = core.submit({"kind": "stream", "verb": "close", "id": 31,
                        "session": sids[0]}, obs.monotonic())
    assert r["valid"] is True, r


def test_wl_checkpoint_verb_migration():
    core = VerifierCore(batch_cap=8, max_sessions=4)
    hists, m = W.bank_batch(37, 2)
    _, r = core.submit({"kind": "stream", "verb": "open", "id": 1,
                        "model": "wl-bank", "wl": m}, obs.monotonic())
    sid = r["session"]
    p, r = core.submit({"kind": "stream", "verb": "append", "id": 2,
                        "session": sid,
                        "history": history_to_edn(list(hists[0]))},
                       obs.monotonic())
    assert r is None
    core.tick()

    # checkpoint with release is a MOVE: the donor forgets the session
    _, r = core.submit({"kind": "stream", "verb": "checkpoint",
                        "id": 3, "session": sid, "release": True},
                       obs.monotonic())
    assert r["ok"] and r["released"], r
    wire = r["checkpoint"]
    assert len(core.sessions) == 0

    core2 = VerifierCore(batch_cap=8)
    _, r = core2.submit({"kind": "stream", "verb": "open", "id": 1,
                         "checkpoint": wire}, obs.monotonic())
    assert r["ok"] and r.get("migrated"), r
    sid2 = r["session"]
    p, r = core2.submit({"kind": "stream", "verb": "append", "id": 2,
                         "session": sid2,
                         "history": history_to_edn(list(hists[1]))},
                        obs.monotonic())
    assert r is None
    done = core2.tick()
    assert len(done) == 1 and done[0][1]["valid"] is True, done
    _, r = core2.submit({"kind": "stream", "verb": "close", "id": 3,
                         "session": sid2}, obs.monotonic())
    assert r["valid"] is True, r


def test_wl_deadline_expiry_carries_kind_family():
    core = VerifierCore(batch_cap=8)
    hists, m = W.bank_batch(43, 1)
    p, r = core.submit({"kind": "wl", "family": "bank", "id": 1,
                        "wl": m,
                        "history": history_to_edn(list(hists[0])),
                        "deadline_ms": 0.0001}, obs.monotonic())
    assert r is None
    time.sleep(0.01)
    done = core.pump(obs.monotonic())
    assert len(done) == 1
    rep = done[0][1]
    assert rep["valid"] == "unknown" and rep["cause"] == "deadline"
    assert rep["kind"] == "wl" and rep["family"] == "bank", rep
    assert abs(sum(rep["stages"].values()) - rep["latency_ms"]) < 1.0
