"""Tier-1: the repo-wide static invariant checker.

Three contracts:

- ``python -m comdb2_tpu.analysis`` exits 0 on the repo at HEAD — every
  future PR passes the checker by construction;
- each seeded violation fixture (tests/fixtures/analysis/) makes it
  exit non-zero naming the expected rule id with a ``file:line`` anchor;
- the budget analyzer's golden contract: every production ``spec_for``
  tier is accepted, and the known-bad configs (2048-step grid, 2048x10
  prefetch, non-(8,128) block, K=9) are rejected.
"""
from __future__ import annotations

import os
import subprocess
import sys

import pytest

from comdb2_tpu import analysis
from comdb2_tpu.analysis import jaxpr_audit, lint, pallas_budget

REPO = analysis.repo_root()
FIXTURES = os.path.join(REPO, "tests", "fixtures", "analysis")

#: fixture -> rule id it must trip (mirrors fixtures/analysis/README.md)
FIXTURE_RULES = {
    "bad_env_jax.py": "jax-env-after-import",
    "bad_multiprocessing.py": "no-multiprocessing",
    "bad_hash_dedup.py": "hash-dedup",
    "bad_dup_cond.py": "dup-cond-closure",
    "bad_keyed_history.py": "keyed-history-wrap",
    "bad_nemesis_completion.py": "nemesis-info-completion",
    "bad_dispatch_loop.py": "per-item-dispatch",
    "bad_txn_dispatch_loop.py": "per-item-dispatch",
    "bad_shrink_dispatch_loop.py": "per-item-dispatch",
    "bad_pack_per_op_loop.py": "per-op-host-loop",
    "bad_pallas_grid.py": "pallas-grid-steps",
    "bad_pallas_prefetch.py": "pallas-prefetch-smem",
    "bad_pallas_block.py": "pallas-block-shape",
    "bad_pallas_k9.py": "pallas-k-cap",
    "bad_unbucketed_shape.py": "jaxpr-unbucketed-shape",
    "bad_unbucketed_dispatch.py": "unbucketed-dispatch-site",
    "bad_mxu_unbucketed_dispatch.py": "unbucketed-dispatch-site",
    "bad_stream_unbucketed_delta.py": "unbucketed-dispatch-site",
    "bad_stream_jnp_checkpoint.py": "host-numpy-checkpoint",
    "bad_unsharded_mesh_dispatch.py": "unbucketed-dispatch-site",
    "bad_vmap_sharded_route.py": "vmap-sharded-oracle",
    "bad_stale_suppression.py": "stale-suppression",
    "bad_raw_clock_dispatch.py": "raw-clock-in-pipeline",
}


def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "comdb2_tpu.analysis", *argv],
        cwd=REPO, capture_output=True, text=True, timeout=300)


# --- the repo itself is clean ------------------------------------------------

def test_repo_scan_is_clean():
    """The acceptance gate: the checker exits 0 on the repo at HEAD
    (full run — lint, production budgets, jaxpr audit incl. the
    abstract traces)."""
    r = _run_cli()
    assert r.returncode == 0, \
        f"checker found violations at HEAD:\n{r.stdout}{r.stderr}"
    assert "OK: 0 findings" in r.stdout


# --- every seeded fixture fails with the right rule --------------------------

def test_fixture_inventory_matches_readme():
    on_disk = {f for f in os.listdir(FIXTURES) if f.endswith(".py")}
    assert on_disk == set(FIXTURE_RULES), \
        "fixtures/analysis/ and FIXTURE_RULES drifted apart"
    # the acceptance floor: >= 16 fixtures across the pass families
    assert len(FIXTURE_RULES) >= 16


@pytest.mark.parametrize("fixture,rule", sorted(FIXTURE_RULES.items()))
def test_fixture_trips_rule(fixture, rule):
    path = os.path.join(FIXTURES, fixture)
    r = _run_cli(path)
    assert r.returncode != 0, f"{fixture} passed the checker"
    line = next((ln for ln in r.stdout.splitlines()
                 if ln.startswith(rule + " ")), None)
    assert line is not None, \
        f"{fixture}: no {rule} finding in:\n{r.stdout}"
    # file:line anchor present and parseable
    loc = line.split(" ", 2)[1]
    fpath, _, lineno = loc.rpartition(":")
    assert fpath.endswith(fixture) and int(lineno) > 0


def test_fixtures_excluded_from_repo_scan():
    files = analysis.collect_files()
    assert files and not any("fixtures" in f for f in files)


def test_hash_dedup_rule_covers_mxu_module():
    """checker/mxu.py imports jax, so the hash-dedup rule is ACTIVE
    there: a hash() snuck into the new engine's dedup path would be a
    finding (the rule keys on the jax import, not a module list — this
    pins that the new engine didn't fall outside it), and the module
    as committed is clean."""
    path = os.path.join(REPO, "comdb2_tpu", "checker", "mxu.py")
    with open(path) as fh:
        src = fh.read()
    seeded = lint.lint_file(path, source=src + "\n_bad = hash((1, 2))\n")
    assert any(f.rule == "hash-dedup" for f in seeded)
    assert [f.format() for f in lint.lint_file(path, source=src)] == []


# --- budget analyzer golden tests --------------------------------------------

def test_budget_accepts_every_production_tier():
    tiers = pallas_budget.production_tiers()
    assert tiers, "no spec_for tier reachable from the bucket ladder"
    for bucket, P, K, spec in tiers:
        findings = pallas_budget.check_spec(
            spec, where=f"spec_for({bucket},P={P},K={K})")
        assert findings == [], [f.format() for f in findings]
    assert pallas_budget.check_production() == []


@pytest.mark.parametrize("cfg,rule", [
    (dict(grid_steps=2048), "pallas-grid-steps"),
    (dict(prefetch_int32=2048 * 10), "pallas-prefetch-smem"),
    (dict(block=(8, 100)), "pallas-block-shape"),
    (dict(block=(3, 128)), "pallas-block-shape"),
    (dict(K=9), "pallas-k-cap"),
    (dict(F=64), "pallas-f-cap"),
])
def test_budget_rejects_known_bad(cfg, rule):
    findings = pallas_budget.check_config(**cfg)
    assert findings and findings[0].rule == rule


@pytest.mark.parametrize("cfg", [
    dict(grid_steps=1024),          # production CHUNK
    dict(grid_steps=1408),          # measured compile bound
    dict(prefetch_int32=1024 * 10),
    dict(block=(8, 128)),
    dict(block=(16, 128)),
    dict(K=8, F=128),
])
def test_budget_accepts_known_good(cfg):
    assert pallas_budget.check_config(**cfg) == []


def test_budget_grid_steps_are_the_dim_product():
    """Grid steps run sequentially, so the Mosaic bound applies to the
    PRODUCT of the grid dims — a (64, 64) grid is 4096 steps and must
    be flagged even though each dim alone is tiny."""
    src = ("from jax.experimental import pallas as pl\n"
           "def run(k, x):\n"
           "    return pl.pallas_call(k, grid=(64, 64))(x)\n")
    fs = pallas_budget.scan_file("<mem>", src)
    assert [f.rule for f in fs] == ["pallas-grid-steps"]
    assert pallas_budget.scan_file(
        "<mem>", src.replace("(64, 64)", "(8, 128)")) == []


def test_budget_table_artifact():
    table = pallas_budget.budget_table()
    assert table.startswith("# Pallas budget table")
    # one row per distinct production tier (head, blank, 2 header rows)
    n_rows = len(table.splitlines()) - 4
    assert n_rows == len(pallas_budget.production_tiers())


# --- jaxpr audit -------------------------------------------------------------

def test_bucket_ladder_matches_fuzz_script():
    """PRODUCTION_BUCKETS mirrors scripts/fuzz_pallas_seg.py; the
    mirror must not drift (every fuzz `bucket = (a, b)` literal is in
    the ladder, checked by the AST scan being clean on the script)."""
    src = os.path.join(REPO, "scripts", "fuzz_pallas_seg.py")
    assert jaxpr_audit.scan_file(src) == []
    with open(src) as fh:
        text = fh.read()
    for ns, nt in pallas_budget.PRODUCTION_BUCKETS:
        assert f"({ns}, {nt})" in text, \
            f"bucket ({ns},{nt}) not exercised by the fuzz script"


def test_bucket_closure():
    assert jaxpr_audit.check_bucket_closure() == []


def test_trace_entry_points_clean():
    """Tracing the engine entry points across every declared bucket
    finds no duplicated cond sub-jaxprs (and traces successfully —
    a trace failure IS a finding)."""
    findings = jaxpr_audit.trace_entry_points()
    assert findings == [], [f.format() for f in findings]


def test_duplicated_cond_branches_detects():
    import jax
    import jax.numpy as jnp
    from jax import lax

    def body(x):
        # non-trivial (>= MIN_BRANCH_EQNS equations), duplicated
        return jnp.sum(jnp.sin(x) * 2.0) + jnp.max(x)

    def f(x):
        # deliberately duplicated branch: the subject under test
        return lax.cond(x[0] > 0, body, body, x)  # analysis: ignore[dup-cond-closure]

    jaxpr = jax.make_jaxpr(f)(jnp.ones(8))
    assert jaxpr_audit.duplicated_cond_branches(jaxpr)


# --- suppression -------------------------------------------------------------

def test_per_line_suppression():
    src = ("import os\nimport jax\n"
           "os.environ['JAX_PLATFORMS'] = 'cpu'"
           "  # analysis: ignore[jax-env-after-import]\n")
    assert lint.lint_file("<mem>", src) == []
    # wrong rule id in the marker does NOT suppress
    src_wrong = src.replace("jax-env-after-import", "hash-dedup")
    assert [f.rule for f in lint.lint_file("<mem>", src_wrong)] == \
        ["jax-env-after-import"]
    # blanket marker suppresses everything on the line
    src_blanket = src.replace("[jax-env-after-import]", "")
    assert lint.lint_file("<mem>", src_blanket) == []


def test_cli_json_artifact(tmp_path):
    out = tmp_path / "findings.json"
    table = tmp_path / "budgets.md"
    r = _run_cli("--json", str(out), "--budget-table", str(table),
                 os.path.join(FIXTURES, "bad_pallas_k9.py"))
    assert r.returncode == 1
    import json
    data = json.loads(out.read_text())
    assert data and data[0]["rule"] == "pallas-k-cap"
    assert table.read_text().startswith("# Pallas budget table")


def test_cli_json_exit_code_regression(tmp_path):
    """``--json`` must not absorb the failure: findings still exit
    non-zero with the artifact written, and a clean file still exits
    zero (with an empty artifact)."""
    import json

    out = tmp_path / "findings.json"
    r = _run_cli("--json", str(out),
                 os.path.join(FIXTURES, "bad_multiprocessing.py"))
    assert r.returncode != 0
    assert json.loads(out.read_text())
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    out2 = tmp_path / "clean.json"
    r = _run_cli("--json", str(out2), str(clean))
    assert r.returncode == 0
    assert json.loads(out2.read_text()) == []


def test_cli_reports_per_pass_timing():
    """Slow passes must be visible: one timed line per pass on
    stderr."""
    r = _run_cli(os.path.join(FIXTURES, "bad_multiprocessing.py"))
    for name in ("lint", "pallas-budget", "jaxpr-audit",
                 "compile-surface", "suppression-audit"):
        assert f"pass {name}:" in r.stderr, r.stderr


def test_cli_programs_artifact(tmp_path):
    progs = tmp_path / "PROGRAMS.md"
    r = _run_cli("--programs", str(progs),
                 os.path.join(FIXTURES, "bad_multiprocessing.py"))
    assert r.returncode == 1            # the fixture still fails
    assert progs.read_text().startswith("# Compile-surface inventory")
