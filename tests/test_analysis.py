"""Tier-1: the repo-wide static invariant checker.

Three contracts:

- ``python -m comdb2_tpu.analysis`` exits 0 on the repo at HEAD — every
  future PR passes the checker by construction;
- each seeded violation fixture (tests/fixtures/analysis/) makes it
  exit non-zero naming the expected rule id with a ``file:line`` anchor;
- the budget analyzer's golden contract: every production ``spec_for``
  tier is accepted, and the known-bad configs (2048-step grid, 2048x10
  prefetch, non-(8,128) block, K=9) are rejected.
"""
from __future__ import annotations

import os
import subprocess
import sys

import pytest

from comdb2_tpu import analysis
from comdb2_tpu.analysis import (dataflow, jaxpr_audit, lifecycle, lint,
                                 pallas_budget)

REPO = analysis.repo_root()
FIXTURES = os.path.join(REPO, "tests", "fixtures", "analysis")

#: fixture -> rule id it must trip (mirrors fixtures/analysis/README.md)
FIXTURE_RULES = {
    "bad_env_jax.py": "jax-env-after-import",
    "bad_multiprocessing.py": "no-multiprocessing",
    "bad_hash_dedup.py": "hash-dedup",
    "bad_dup_cond.py": "dup-cond-closure",
    "bad_keyed_history.py": "keyed-history-wrap",
    "bad_nemesis_completion.py": "nemesis-info-completion",
    "bad_dispatch_loop.py": "per-item-dispatch",
    "bad_txn_dispatch_loop.py": "per-item-dispatch",
    "bad_shrink_dispatch_loop.py": "per-item-dispatch",
    "bad_pack_per_op_loop.py": "per-op-host-loop",
    "bad_pallas_grid.py": "pallas-grid-steps",
    "bad_pallas_prefetch.py": "pallas-prefetch-smem",
    "bad_pallas_block.py": "pallas-block-shape",
    "bad_pallas_k9.py": "pallas-k-cap",
    "bad_unbucketed_shape.py": "jaxpr-unbucketed-shape",
    "bad_unbucketed_dispatch.py": "unbucketed-dispatch-site",
    "bad_mxu_unbucketed_dispatch.py": "unbucketed-dispatch-site",
    "bad_stream_unbucketed_delta.py": "unbucketed-dispatch-site",
    "bad_stream_megabatch_delta.py": "unbucketed-dispatch-site",
    "bad_wl_unbucketed_dispatch.py": "unbucketed-dispatch-site",
    "bad_stream_jnp_checkpoint.py": "host-numpy-checkpoint",
    "bad_unsharded_mesh_dispatch.py": "unbucketed-dispatch-site",
    "bad_vmap_sharded_route.py": "vmap-sharded-oracle",
    "bad_stale_suppression.py": "stale-suppression",
    "bad_raw_clock_dispatch.py": "raw-clock-in-pipeline",
    "bad_ready_before_publish.py": "publish-before-ready",
    "bad_close_before_deregister.py": "deregister-before-close",
    "bad_log_before_success.py": "log-after-success",
    "bad_leaked_pin.py": "release-in-finally",
    "bad_stale_ttl_timestamp.py": "fresh-deadline-timestamp",
    "bad_kill_no_wait.py": "wait-after-kill",
    "bad_sync_readback_pump.py": "sync-readback-in-pump",
    "bad_per_item_transfer.py": "per-item-transfer",
}


def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "comdb2_tpu.analysis", *argv],
        cwd=REPO, capture_output=True, text=True, timeout=300)


# --- the repo itself is clean ------------------------------------------------

def test_repo_scan_is_clean():
    """The acceptance gate: the checker exits 0 on the repo at HEAD
    (full run — lint, production budgets, jaxpr audit incl. the
    abstract traces)."""
    r = _run_cli()
    assert r.returncode == 0, \
        f"checker found violations at HEAD:\n{r.stdout}{r.stderr}"
    assert "OK: 0 findings" in r.stdout


# --- every seeded fixture fails with the right rule --------------------------

def test_fixture_inventory_matches_readme():
    on_disk = {f for f in os.listdir(FIXTURES) if f.endswith(".py")}
    assert on_disk == set(FIXTURE_RULES), \
        "fixtures/analysis/ and FIXTURE_RULES drifted apart"
    # the acceptance floor: >= 30 fixtures across the pass families
    assert len(FIXTURE_RULES) >= 30


@pytest.mark.parametrize("fixture,rule", sorted(FIXTURE_RULES.items()))
def test_fixture_trips_rule(fixture, rule):
    path = os.path.join(FIXTURES, fixture)
    r = _run_cli(path)
    assert r.returncode != 0, f"{fixture} passed the checker"
    line = next((ln for ln in r.stdout.splitlines()
                 if ln.startswith(rule + " ")), None)
    assert line is not None, \
        f"{fixture}: no {rule} finding in:\n{r.stdout}"
    # file:line anchor present and parseable
    loc = line.split(" ", 2)[1]
    fpath, _, lineno = loc.rpartition(":")
    assert fpath.endswith(fixture) and int(lineno) > 0


def test_fixtures_excluded_from_repo_scan():
    files = analysis.collect_files()
    assert files and not any("fixtures" in f for f in files)


def test_hash_dedup_rule_covers_mxu_module():
    """checker/mxu.py imports jax, so the hash-dedup rule is ACTIVE
    there: a hash() snuck into the new engine's dedup path would be a
    finding (the rule keys on the jax import, not a module list — this
    pins that the new engine didn't fall outside it), and the module
    as committed is clean."""
    path = os.path.join(REPO, "comdb2_tpu", "checker", "mxu.py")
    with open(path) as fh:
        src = fh.read()
    seeded = lint.lint_file(path, source=src + "\n_bad = hash((1, 2))\n")
    assert any(f.rule == "hash-dedup" for f in seeded)
    assert [f.format() for f in lint.lint_file(path, source=src)] == []


# --- budget analyzer golden tests --------------------------------------------

def test_budget_accepts_every_production_tier():
    tiers = pallas_budget.production_tiers()
    assert tiers, "no spec_for tier reachable from the bucket ladder"
    for bucket, P, K, spec in tiers:
        findings = pallas_budget.check_spec(
            spec, where=f"spec_for({bucket},P={P},K={K})")
        assert findings == [], [f.format() for f in findings]
    assert pallas_budget.check_production() == []


@pytest.mark.parametrize("cfg,rule", [
    (dict(grid_steps=2048), "pallas-grid-steps"),
    (dict(prefetch_int32=2048 * 10), "pallas-prefetch-smem"),
    (dict(block=(8, 100)), "pallas-block-shape"),
    (dict(block=(3, 128)), "pallas-block-shape"),
    (dict(K=9), "pallas-k-cap"),
    (dict(F=64), "pallas-f-cap"),
])
def test_budget_rejects_known_bad(cfg, rule):
    findings = pallas_budget.check_config(**cfg)
    assert findings and findings[0].rule == rule


@pytest.mark.parametrize("cfg", [
    dict(grid_steps=1024),          # production CHUNK
    dict(grid_steps=1408),          # measured compile bound
    dict(prefetch_int32=1024 * 10),
    dict(block=(8, 128)),
    dict(block=(16, 128)),
    dict(K=8, F=128),
])
def test_budget_accepts_known_good(cfg):
    assert pallas_budget.check_config(**cfg) == []


def test_budget_grid_steps_are_the_dim_product():
    """Grid steps run sequentially, so the Mosaic bound applies to the
    PRODUCT of the grid dims — a (64, 64) grid is 4096 steps and must
    be flagged even though each dim alone is tiny."""
    src = ("from jax.experimental import pallas as pl\n"
           "def run(k, x):\n"
           "    return pl.pallas_call(k, grid=(64, 64))(x)\n")
    fs = pallas_budget.scan_file("<mem>", src)
    assert [f.rule for f in fs] == ["pallas-grid-steps"]
    assert pallas_budget.scan_file(
        "<mem>", src.replace("(64, 64)", "(8, 128)")) == []


def test_budget_table_artifact():
    table = pallas_budget.budget_table()
    assert table.startswith("# Pallas budget table")
    # one row per distinct production tier (head, blank, 2 header rows)
    n_rows = len(table.splitlines()) - 4
    assert n_rows == len(pallas_budget.production_tiers())


# --- jaxpr audit -------------------------------------------------------------

def test_bucket_ladder_matches_fuzz_script():
    """PRODUCTION_BUCKETS mirrors scripts/fuzz_pallas_seg.py; the
    mirror must not drift (every fuzz `bucket = (a, b)` literal is in
    the ladder, checked by the AST scan being clean on the script)."""
    src = os.path.join(REPO, "scripts", "fuzz_pallas_seg.py")
    assert jaxpr_audit.scan_file(src) == []
    with open(src) as fh:
        text = fh.read()
    for ns, nt in pallas_budget.PRODUCTION_BUCKETS:
        assert f"({ns}, {nt})" in text, \
            f"bucket ({ns},{nt}) not exercised by the fuzz script"


def test_bucket_closure():
    assert jaxpr_audit.check_bucket_closure() == []


def test_trace_entry_points_clean():
    """Tracing the engine entry points across every declared bucket
    finds no duplicated cond sub-jaxprs (and traces successfully —
    a trace failure IS a finding)."""
    findings = jaxpr_audit.trace_entry_points()
    assert findings == [], [f.format() for f in findings]


def test_duplicated_cond_branches_detects():
    import jax
    import jax.numpy as jnp
    from jax import lax

    def body(x):
        # non-trivial (>= MIN_BRANCH_EQNS equations), duplicated
        return jnp.sum(jnp.sin(x) * 2.0) + jnp.max(x)

    def f(x):
        # deliberately duplicated branch: the subject under test
        return lax.cond(x[0] > 0, body, body, x)  # analysis: ignore[dup-cond-closure]

    jaxpr = jax.make_jaxpr(f)(jnp.ones(8))
    assert jaxpr_audit.duplicated_cond_branches(jaxpr)


# --- suppression -------------------------------------------------------------

def test_per_line_suppression():
    src = ("import os\nimport jax\n"
           "os.environ['JAX_PLATFORMS'] = 'cpu'"
           "  # analysis: ignore[jax-env-after-import]\n")
    assert lint.lint_file("<mem>", src) == []
    # wrong rule id in the marker does NOT suppress
    src_wrong = src.replace("jax-env-after-import", "hash-dedup")
    assert [f.rule for f in lint.lint_file("<mem>", src_wrong)] == \
        ["jax-env-after-import"]
    # blanket marker suppresses everything on the line
    src_blanket = src.replace("[jax-env-after-import]", "")
    assert lint.lint_file("<mem>", src_blanket) == []


def test_cli_json_artifact(tmp_path):
    out = tmp_path / "findings.json"
    table = tmp_path / "budgets.md"
    r = _run_cli("--json", str(out), "--budget-table", str(table),
                 os.path.join(FIXTURES, "bad_pallas_k9.py"))
    assert r.returncode == 1
    import json
    data = json.loads(out.read_text())
    assert data and data[0]["rule"] == "pallas-k-cap"
    assert table.read_text().startswith("# Pallas budget table")


def test_cli_json_exit_code_regression(tmp_path):
    """``--json`` must not absorb the failure: findings still exit
    non-zero with the artifact written, and a clean file still exits
    zero (with an empty artifact)."""
    import json

    out = tmp_path / "findings.json"
    r = _run_cli("--json", str(out),
                 os.path.join(FIXTURES, "bad_multiprocessing.py"))
    assert r.returncode != 0
    assert json.loads(out.read_text())
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    out2 = tmp_path / "clean.json"
    r = _run_cli("--json", str(out2), str(clean))
    assert r.returncode == 0
    assert json.loads(out2.read_text()) == []


def test_cli_reports_per_pass_timing():
    """Slow passes must be visible: one timed line per pass on
    stderr."""
    r = _run_cli(os.path.join(FIXTURES, "bad_multiprocessing.py"))
    for name in ("lint", "pallas-budget", "jaxpr-audit",
                 "compile-surface", "lifecycle", "dataflow",
                 "suppression-audit"):
        assert f"pass {name}:" in r.stderr, r.stderr


def test_cli_programs_artifact(tmp_path):
    progs = tmp_path / "PROGRAMS.md"
    r = _run_cli("--programs", str(progs),
                 os.path.join(FIXTURES, "bad_multiprocessing.py"))
    assert r.returncode == 1            # the fixture still fails
    assert progs.read_text().startswith("# Compile-surface inventory")


# --- pass 5: lifecycle & dataflow ---------------------------------------------

#: the pass-5 rule ids (lifecycle + dataflow)
PASS5_RULES = {"publish-before-ready", "deregister-before-close",
               "log-after-success", "release-in-finally",
               "fresh-deadline-timestamp", "wait-after-kill",
               "sync-readback-in-pump", "per-item-transfer"}


def _pass5_rules(path):
    return ({f.rule for f in lifecycle.scan_file(path)}
            | {f.rule for f in dataflow.scan_files([path])})


@pytest.mark.parametrize("fixture,rule", sorted(FIXTURE_RULES.items()))
def test_pass5_rules_exclusive(fixture, rule):
    """The acceptance gate's exclusivity half: each pass-5 fixture
    trips exactly its own pass-5 rule, and NO pre-existing fixture
    trips any pass-5 rule (a cross-rule false positive on the seeded
    corpus would mean the new analyzers over-match)."""
    fired = _pass5_rules(os.path.join(FIXTURES, fixture))
    if rule in PASS5_RULES:
        assert fired == {rule}, (fixture, fired)
    else:
        assert fired == set(), (fixture, fired)


#: (tag, rule, pre-fix excerpt, post-fix excerpt) — the PR-12
#: review-round bugs, reproduced from the pre-fix code shape so the
#: rules provably catch what the reviews caught by hand
PR12_EXCERPTS = [
    ("shutdown-close-order", "deregister-before-close",
     # service/daemon.py pre-fix: listener closed before the withdraw
     '''
class D:
    def _shutdown(self):
        for p, reply in self.core.tick(monotonic()):
            self._send(p.ctx, reply)
        self._lsock.close()
        self._sel.close()
        self._pmux_withdraw()
''',
     '''
class D:
    def _shutdown(self):
        self._pmux_withdraw()
        for p, reply in self.core.tick(monotonic()):
            self._send(p.ctx, reply)
        self._lsock.close()
        self._sel.close()
'''),
    ("memo-log-order", "log-after-success",
     # models/memo.py pre-fix: the extend-call log appended BEFORE the
     # closure ran — a MemoOverflow mid-extend poisoned every restore
     '''
class IncrementalMemo:
    def extend(self, ops):
        self._log.append(tuple(ops))
        self._closure(ops)
        self._depth += len(ops)
''',
     '''
class IncrementalMemo:
    def extend(self, ops):
        self._closure(ops)
        self._depth += len(ops)
        self._log.append(tuple(ops))
'''),
    ("stream-close-pin-leak", "release-in-finally",
     # client.py pre-fix: a close whose failover also failed leaked
     # the pin (the node's client parked in _parting forever)
     '''
class RoutedStream:
    def close(self):
        out = self._client.stream_close(self.sid)
        self._router._unpin(self._node)
        return out
''',
     '''
class RoutedStream:
    def close(self):
        try:
            out = self._client.stream_close(self.sid)
        finally:
            self._router._unpin(self._node)
        return out
'''),
    ("route-stale-ttl", "fresh-deadline-timestamp",
     # client.py pre-fix: blacklist TTL anchored at walk start — a
     # hung connect burned the timeout, so the deadline was already
     # expired when written and the node got re-dialed hot
     '''
def _route(self, cls):
    now = monotonic()
    for name in self._ring.walk(cls):
        try:
            return self._dial(name)
        except OSError:
            self._blacklist[name] = now + self.blacklist_ttl_s
    return None
''',
     '''
def _route(self, cls):
    for name in self._ring.walk(cls):
        try:
            return self._dial(name)
        except OSError:
            self._blacklist[name] = monotonic() + self.blacklist_ttl_s
    return None
'''),
]


@pytest.mark.parametrize("tag,rule,bad,good",
                         PR12_EXCERPTS,
                         ids=[e[0] for e in PR12_EXCERPTS])
def test_pass5_reproduces_pr12_review_bugs(tag, rule, bad, good):
    """The acceptance gate's reproduction half: reverting >= 3 of the
    PR-12 review-round fixes (as faithful pre-fix code excerpts) makes
    the matching rule fire, and each post-fix twin is clean — the
    rules encode exactly the orderings the reviews fixed by hand."""
    fired = [f.rule for f in lifecycle.scan_file("<mem>.py", bad)]
    assert fired == [rule], (tag, fired)
    assert lifecycle.scan_file("<mem>.py", good) == [], tag


def test_dataflow_deferred_finalize_exempt(tmp_path):
    """The ring's contract: readbacks in the DEFERRED finalize closure
    a hot path stages are the sanctioned pattern — only an inline
    readback on the beat itself is a finding."""
    inline = tmp_path / "inline_dispatch.py"
    inline.write_text(
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "def pump(core):\n"
        "    out = jnp.sum(core.buf)\n"
        "    return np.asarray(out)\n")
    deferred = tmp_path / "deferred_dispatch.py"
    deferred.write_text(
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "def pump(core):\n"
        "    out = jnp.sum(core.buf)\n"
        "    def finalize():\n"
        "        return np.asarray(out)\n"
        "    core.ring.append(finalize)\n")
    assert [f.rule for f in dataflow.scan_files([str(inline)])] == \
        ["sync-readback-in-pump"]
    assert dataflow.scan_files([str(deferred)]) == []


def test_pass5_suppression_live_and_stale(tmp_path):
    """The suppression-audit path for BOTH pass-5 analyzers: a live
    marker suppresses its finding without becoming stale (dataflow's
    whole-set raw_paths re-scan), and a marker on a clean line is a
    stale-suppression finding (lifecycle's per-file raw_file
    re-scan)."""
    live = tmp_path / "pump_dispatch.py"
    live.write_text(
        "import jax.numpy as jnp\n"
        "def pump(core):\n"
        "    x = jnp.sum(core.buf)\n"
        "    return float(x)"
        "  # analysis: ignore[sync-readback-in-pump]\n")
    stale = tmp_path / "svc_dispatch.py"
    stale.write_text(
        "def retire(proc):\n"
        "    proc.terminate()\n"
        "    proc.wait()  # analysis: ignore[wait-after-kill]\n")
    # the live marker suppresses, and the audit does not flag it
    assert analysis.run_paths([str(live)]) == []
    assert analysis.audit_suppressions([str(live)]) == []
    # the stale marker survives no rule and IS the finding
    fired = [f.rule for f in analysis.run_paths([str(stale)])]
    assert fired == ["stale-suppression"], fired


def test_pass5_json_exit_code(tmp_path):
    """``--json`` over a pass-5 fixture: non-zero exit with the rule
    in the artifact (the artifact records the failure, it never
    absorbs it)."""
    import json

    out = tmp_path / "findings.json"
    r = _run_cli("--json", str(out),
                 os.path.join(FIXTURES, "bad_sync_readback_pump.py"))
    assert r.returncode == 1
    rules = {f["rule"] for f in json.loads(out.read_text())}
    assert "sync-readback-in-pump" in rules, rules


# --- --changed incremental mode ----------------------------------------------

def _git(root, *args):
    subprocess.run(["git", "-c", "user.email=t@t.invalid",
                    "-c", "user.name=t", *args],
                   cwd=root, check=True, capture_output=True)


def test_changed_mode_agrees_with_full_run(tmp_path):
    """The acceptance gate: over a touched-file subset, the
    incremental ``--changed`` file set produces exactly the findings
    the full run attributes to those files — modified-tracked and
    untracked files are both in, committed-clean files are out."""
    root = str(tmp_path)
    os.makedirs(os.path.join(root, "scripts"))
    _git(root, "init", "-q")
    clean = os.path.join(root, "scripts", "clean.py")
    with open(clean, "w") as fh:
        fh.write("x = 1\n")
    tracked = os.path.join(root, "scripts", "svc_dispatch.py")
    with open(tracked, "w") as fh:
        fh.write("def retire(p):\n    p.terminate()\n    p.wait()\n")
    _git(root, "add", "-A")
    _git(root, "commit", "-qm", "seed")
    # revert the wait-after-kill fix in the tracked file...
    with open(tracked, "w") as fh:
        fh.write("def retire(p):\n    p.terminate()\n")
    # ... and add an untracked file with a per-item transfer loop
    new = os.path.join(root, "scripts", "xfer_dispatch.py")
    with open(new, "w") as fh:
        fh.write("import jax\ndef push(items):\n"
                 "    for it in items:\n        jax.device_put(it)\n")
    changed = analysis.changed_files("HEAD", root=root)
    assert sorted(os.path.basename(p) for p in changed) == \
        ["svc_dispatch.py", "xfer_dispatch.py"]
    inc = {(os.path.basename(f.path), f.rule)
           for f in analysis.run_paths(changed)}
    full = {(os.path.basename(f.path), f.rule)
            for f in analysis.run_paths(analysis.collect_files(root))
            if f.path in set(changed)}
    assert inc == full == {("svc_dispatch.py", "wait-after-kill"),
                           ("xfer_dispatch.py", "per-item-transfer")}


def test_changed_cli_paths_and_bad_ref():
    """CLI wiring: ``--changed`` with explicit paths is an error, and
    an unresolvable ref exits 2 (distinct from the findings exit 1)."""
    r = _run_cli("--changed", "HEAD",
                 os.path.join(FIXTURES, "bad_multiprocessing.py"))
    assert r.returncode == 2
    r = _run_cli("--changed", "no-such-ref-xyz")
    assert r.returncode == 2
    assert "--changed" in r.stderr
