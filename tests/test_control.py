"""Control plane tests: sessions, escaping, parallel exec, net,
nemesis grudges and fault routing — all against recording/local
transports (no cluster required)."""

import threading

import pytest

from comdb2_tpu import control
from comdb2_tpu.control import net as net_ns
from comdb2_tpu.control import util as cutil
from comdb2_tpu.control.remote import ExecResult, LocalRemote, RecordingRemote
from comdb2_tpu.harness import nemesis as N


# --- command building -------------------------------------------------------

def test_escape_and_build():
    assert control.build_cmd("echo", "hi there") == "echo 'hi there'"
    assert control.build_cmd("ls", "-l") == "ls -l"
    assert control.build_cmd("echo", control.lit("a && b")) == "echo a && b"
    assert control.escape(["a", "b c"]) == "a 'b c'"
    assert control.escape("") == "''"


def test_session_wrap_sudo_and_cd():
    s = control.Session("h", RecordingRemote(), sudo="root", cwd="/tmp")
    cmd = s.wrap("ls -l")
    assert cmd == "sudo -S -u root sh -c 'cd /tmp && ls -l'"


# --- exec over transports ---------------------------------------------------

def test_local_remote_exec():
    s = control.Session("localhost", LocalRemote())
    with control.with_session(s):
        assert control.exec_("echo", "hello") == "hello"
        with pytest.raises(control.RemoteError):
            control.exec_("false")
        assert control.exec_("false", check=False) == ""


def test_exec_requires_session():
    with pytest.raises(RuntimeError, match="no control session"):
        control.exec_("echo", "x")


def test_on_nodes_binds_per_thread_sessions():
    rec = RecordingRemote()
    test = {"nodes": ["n1", "n2", "n3"], "remote": rec}
    hosts = {}

    def f(test_, node):
        hosts[node] = control.current_session().host
        control.exec_("hostname")
        return node.upper()

    results = control.on_nodes(test, f)
    assert results == {"n1": "N1", "n2": "N2", "n3": "N3"}
    assert hosts == {"n1": "n1", "n2": "n2", "n3": "n3"}
    assert sorted(h for h, _ in rec.commands) == ["n1", "n2", "n3"]


def test_su_runs_as_root():
    rec = RecordingRemote()
    with control.on("h", rec):
        control.su("whoami")
    assert rec.commands[0][1].startswith("sudo -S -u root")


def test_control_util_helpers():
    rec = RecordingRemote(
        responder=lambda h, c: ExecResult(0, "/tmp/tmp.X", "")
        if "mktemp" in c else None)
    with control.on("h", rec):
        assert cutil.tmp_dir() == "/tmp/tmp.X"
        assert cutil.exists("/etc/hosts") is True
        cutil.grepkill("myproc")
    cmds = [c for _, c in rec.commands]
    assert any("test -e /etc/hosts" in c for c in cmds)
    assert any("pkill -KILL -f myproc" in c for c in cmds)


# --- net --------------------------------------------------------------------

def _ip_responder(host, cmd):
    if cmd.startswith("getent hosts"):
        name = cmd.split()[-1]
        return ExecResult(0, f"10.0.0.{name[-1]} {name}", "")
    return None


def test_iptables_drop_and_heal():
    rec = RecordingRemote(responder=_ip_responder)
    test = {"nodes": ["n1", "n2"], "remote": rec}
    net = net_ns.IptablesNet()
    net.drop(test, "n1", "n2")
    cmds = [(h, c) for h, c in rec.commands if "iptables" in c]
    assert len(cmds) == 1
    host, cmd = cmds[0]
    assert host == "n2"
    assert "iptables -A INPUT -s 10.0.0.1 -j DROP -w" in cmd

    rec.commands.clear()
    net.heal(test)
    heals = [(h, c) for h, c in rec.commands if "iptables -F" in c]
    assert {h for h, _ in heals} == {"n1", "n2"}


def test_net_slow_flaky_fast():
    rec = RecordingRemote()
    test = {"nodes": ["n1"], "remote": rec}
    net = net_ns.IptablesNet()
    net.slow(test)
    net.flaky(test)
    net.fast(test)
    cmds = [c for _, c in rec.commands]
    assert any("netem delay 50ms 10ms distribution normal" in c
               for c in cmds)
    assert any("netem loss 20% 75%" in c for c in cmds)
    assert any("qdisc del dev eth0 root" in c for c in cmds)


# --- grudges ----------------------------------------------------------------

def test_bisect_and_split_one():
    assert N.bisect([1, 2, 3, 4, 5]) == [[1, 2], [3, 4, 5]]
    loner, rest = N.split_one([1, 2, 3], loner=2)
    assert loner == [2] and rest == [1, 3]


def test_complete_grudge():
    g = N.complete_grudge([[1, 2], [3, 4, 5]])
    assert g[1] == {3, 4, 5}
    assert g[4] == {1, 2}
    assert len(g) == 5


def test_bridge_grudge():
    g = N.bridge([1, 2, 3, 4, 5])
    # node 3 is the bridge: snubs nobody, nobody snubs it
    assert 3 not in g
    assert all(3 not in s for s in g.values())
    assert g[1] == {4, 5}
    assert g[4] == {1, 2}


def test_majorities_ring_invariants():
    nodes = [1, 2, 3, 4, 5]
    g = N.majorities_ring(nodes)
    assert set(g) == set(nodes)
    seen_majorities = set()
    for n, dropped in g.items():
        visible = set(nodes) - dropped
        assert n in visible
        assert len(visible) >= N.majority(len(nodes))
        seen_majorities.add(frozenset(visible))
    # no two nodes see the same majority
    assert len(seen_majorities) == len(nodes)


# --- partitioner / nemesis clients ------------------------------------------

def test_partitioner_start_stop():
    rec = RecordingRemote(responder=_ip_responder)
    test = {"nodes": ["n1", "n2", "n3", "n4"], "remote": rec,
            "net": net_ns.IptablesNet()}
    nem = N.partition_halves().setup(test, None)
    rec.commands.clear()
    r = nem.invoke(test, {"type": "info", "f": "start", "value": None})
    assert r["type"] == "info" and "Cut off" in r["value"]
    drops = [c for _, c in rec.commands if "-j DROP" in c]
    # complete grudge between {n1,n2} and {n3,n4}: 2*2 directed pairs,
    # each dropped at the destination => 8 rules
    assert len(drops) == 8
    rec.commands.clear()
    r = nem.invoke(test, {"type": "info", "f": "stop", "value": None})
    assert r["value"] == "fully connected"
    assert any("iptables -F" in c for _, c in rec.commands)


def test_compose_routes_and_renames():
    class Recorder(N.client_ns.Client):
        def __init__(self):
            self.fs = []

        def invoke(self, test, op):
            self.fs.append(op["f"])
            return dict(op)

    a, b = Recorder(), Recorder()
    nem = N.compose([(frozenset({"start", "stop"}), a),
                     ({"kill-start": "start"}, b)])
    nem.invoke({}, {"type": "info", "f": "start"})
    out = nem.invoke({}, {"type": "info", "f": "kill-start"})
    assert a.fs == ["start"]
    assert b.fs == ["start"]          # renamed on the way in
    assert out["f"] == "kill-start"   # restored on the way out
    with pytest.raises(ValueError):
        nem.invoke({}, {"type": "info", "f": "nope"})


def test_hammer_time_stop_cont():
    rec = RecordingRemote()
    test = {"nodes": ["n1", "n2"], "remote": rec}
    nem = N.hammer_time("comdb2", targeter=lambda ns: ns[0])
    r = nem.invoke(test, {"type": "info", "f": "start", "value": None})
    assert r["value"] == {"n1": ["paused", "comdb2"]}
    assert any("killall -s STOP comdb2" in c for h, c in rec.commands
               if h == "n1")
    r2 = nem.invoke(test, {"type": "info", "f": "start", "value": None})
    assert "already disrupting" in r2["value"]
    r3 = nem.invoke(test, {"type": "info", "f": "stop", "value": None})
    assert r3["value"] == {"n1": ["resumed", "comdb2"]}
    r4 = nem.invoke(test, {"type": "info", "f": "stop", "value": None})
    assert r4["value"] == "not-started"


def test_clock_scrambler_sets_dates():
    rec = RecordingRemote()
    test = {"nodes": ["n1", "n2"], "remote": rec}
    nem = N.clock_scrambler(60)
    r = nem.invoke(test, {"type": "info", "f": "scramble", "value": None})
    assert set(r["value"]) == {"n1", "n2"}
    assert all("date +%s -s" in c for _, c in rec.commands)
    nem.teardown(test)


def test_full_run_with_partition_nemesis(tmp_path):
    """Phase-5 integration: a real harness run over the atom SUT where
    the nemesis partitions 'nodes' through the recording transport."""
    from comdb2_tpu.harness import core, fake
    from comdb2_tpu.harness import generator as G
    from comdb2_tpu.models import model as M

    rec = RecordingRemote(responder=_ip_responder)
    state = fake.Atom()
    t = fake.noop_test()
    t.update({
        "nodes": ["n1", "n2", "n3", "n4", "n5"],
        "concurrency": 5,
        "name": "partition-run",
        "store-root": str(tmp_path / "store"),
        "remote": rec,
        "net": net_ns.IptablesNet(),
        "db": fake.atom_db(state),
        "client": fake.atom_client(state),
        "model": M.cas_register(),
        "nemesis": N.partition_random_halves(),
        "generator": G.nemesis(
            G.seq([{"type": "info", "f": "start", "value": None},
                   {"type": "info", "f": "stop", "value": None}]),
            G.limit(40, G.cas_gen)),
    })
    result = core.run(t)
    assert result["results"]["valid?"] is True
    nem_ops = [op for op in result["history"] if op.process == "nemesis"]
    assert len(nem_ops) == 4
    assert any("Cut off" in str(op.value) for op in nem_ops)
    assert any("-j DROP" in c for _, c in rec.commands)
    assert any("iptables -F" in c for _, c in rec.commands)


def test_db_setup_can_use_control_api(tmp_path):
    """core.run's node lifecycle must bind control sessions so DB/OS
    implementations can call control.exec_/su directly."""
    from comdb2_tpu.harness import core, db as db_ns, fake
    from comdb2_tpu.harness import generator as G
    from comdb2_tpu.models import model as M

    rec = RecordingRemote()

    class ShellDB(db_ns.DB):
        def setup(self, test, node):
            control.su("systemctl", "start", "mydb")

        def teardown(self, test, node):
            control.su("systemctl", "stop", "mydb")

    state = fake.Atom()
    t = fake.noop_test()
    t.update({"nodes": ["n1", "n2"], "concurrency": 2,
              "name": "shelldb", "store-root": str(tmp_path / "store"),
              "remote": rec, "db": ShellDB(),
              "client": fake.atom_client(state),
              "model": M.cas_register(),
              "generator": G.clients(G.limit(4, G.cas_gen))})
    result = core.run(t)
    assert result["results"]["valid?"] is True
    starts = [h for h, c in rec.commands if "systemctl start" in c]
    stops = [h for h, c in rec.commands if "systemctl stop" in c]
    assert sorted(starts) == ["n1", "n2"]
    # cycle! tears down first, then run teardown at the end: 2 per node
    assert sorted(stops) == ["n1", "n1", "n2", "n2"]


def test_nemesis_time_compiles_real_helpers(tmp_path):
    """Compile the bump/strobe C helpers locally and check their argv
    contract (without actually setting the clock)."""
    import subprocess

    from comdb2_tpu.harness import nemesis_time as NT

    import os
    s = control.Session("localhost", LocalRemote(),
                        root=os.geteuid() == 0)
    with control.with_session(s):
        NT.install(install_dir=str(tmp_path))
    for name in ("bump-time", "strobe-time"):
        binary = tmp_path / name
        assert binary.exists()
        p = subprocess.run([str(binary)], capture_output=True, text=True)
        assert p.returncode == 2
        assert "usage" in p.stderr


def test_heal_all_and_loop():
    from comdb2_tpu.harness import cluster

    rec = RecordingRemote()
    test = {"nodes": ["n1"], "remote": rec}
    cluster.heal_all(test, processes=["comdb2"])
    cmds = [c for _, c in rec.commands]
    assert any("iptables -F" in c for c in cmds)
    assert any("killall -s CONT comdb2" in c for c in cmds)

    runs = []
    def make_test():
        return {"n": len(runs)}
    def run_fn(t):
        runs.append(t)
        return {"results": {"valid?": len(runs) < 3}}
    n = cluster.test_loop(make_test, run_fn, max_runs=10)
    assert n == 2 and len(runs) == 3
