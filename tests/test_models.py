"""Phase 1 tests: models and state-space memoization.

Behavioral parity targets: knossos/model.clj:48-161, jepsen/model.clj:58-105,
knossos/model/memo.clj:93-196.
"""

import numpy as np
import pytest

from comdb2_tpu.models import (
    register, cas_register, cas_register_comdb2, mutex, multi_register,
    set_model, unordered_queue, fifo_queue, step,
    memo, memoize_model, MemoOverflow,
)
from comdb2_tpu.ops import invoke, ok, pack_history


def test_register():
    m = register()
    m = step(m, "write", 3)
    assert step(m, "read", 3) == m
    assert step(m, "read", 4) is None
    assert step(m, "read", None) == m  # unknown read matches anything
    assert step(step(m, "write", 5), "read", 5) is not None


def test_cas_register():
    m = cas_register(0)
    assert step(m, "cas", (0, 2)).value == 2
    assert step(m, "cas", (1, 2)) is None
    assert step(m, "write", 9).value == 9
    assert step(m, "read", 0) == m
    assert step(m, "read", 1) is None
    # inconsistency is absorbing
    assert step(step(m, "read", 1), "write", 3) is None


def test_cas_register_comdb2_tuple_values():
    from comdb2_tpu.ops.kv import tuple_

    m = cas_register_comdb2(None)
    m = step(m, "write", tuple_(7, 1))        # key 7, value 1
    assert m.value == 1
    assert step(m, "read", tuple_(7, 1)) == m
    assert step(m, "cas", tuple_(7, (1, 2))).value == 2
    assert step(m, "cas", tuple_(7, (3, 2))) is None
    # bare 2-tuples are cas pairs, NOT key wrappers — must not unwrap
    m2 = cas_register_comdb2(1)
    assert step(m2, "cas", (1, 5)).value == 5


def test_mutex():
    m = mutex()
    m2 = step(m, "acquire", None)
    assert m2 is not None
    assert step(m2, "acquire", None) is None
    assert step(m2, "release", None) == m
    assert step(m, "release", None) is None


def test_multi_register():
    m = multi_register({"x": 0, "y": 0})
    m2 = step(m, "txn", (("write", "x", 1), ("read", "y", 0)))
    assert m2 is not None
    assert step(m2, "txn", (("read", "x", 1),)) is not None
    assert step(m2, "txn", (("read", "x", 0),)) is None


def test_set_model():
    m = set_model()
    m = step(m, "add", 1)
    m = step(m, "add", 2)
    assert step(m, "read", (1, 2)) == m
    assert step(m, "read", (1,)) is None
    assert step(m, "read", None) == m


def test_queues():
    uq = unordered_queue()
    uq = step(uq, "enqueue", 1)
    uq = step(uq, "enqueue", 2)
    assert step(uq, "dequeue", 2) is not None   # any order ok
    assert step(uq, "dequeue", 3) is None

    fq = fifo_queue()
    fq = step(fq, "enqueue", 1)
    fq = step(fq, "enqueue", 2)
    assert step(fq, "dequeue", 1) is not None
    assert step(fq, "dequeue", 2) is None       # must be FIFO


def test_memoize_register():
    transitions = [("write", 0), ("write", 1), ("read", 0), ("read", 1)]
    mm = memoize_model(register(), transitions)
    # states: None, 0, 1
    assert mm.n_states == 3
    assert mm.n_transitions == 4
    s0 = 0
    s_after_w0 = mm.step_id(s0, 0)
    assert s_after_w0 != -1
    # read 0 in that state loops; read 1 is inconsistent
    assert mm.step_id(s_after_w0, 2) == s_after_w0
    assert mm.step_id(s_after_w0, 3) == -1
    # write is total: no -1 anywhere in write columns
    assert (mm.succ[:, 0] >= 0).all() and (mm.succ[:, 1] >= 0).all()


def test_memo_from_history():
    h = [invoke(0, "write", 1), ok(0, "write", 1),
         invoke(1, "cas", (1, 2)), ok(1, "cas", (1, 2)),
         invoke(0, "read", None), ok(0, "read", 2)]
    p = pack_history(h)
    mm = memo(cas_register(), p)
    # succ has one column per distinct history transition
    assert mm.succ.shape[1] == p.n_transitions
    # replay sequentially through the table
    s = 0
    for i in range(len(p)):
        if p.type[i] == 0:  # invoke
            s = mm.step_id(s, int(p.trans[i]))
            assert s != -1
    assert mm.states[s].value == 2


def test_memo_overflow():
    transitions = [("add", i) for i in range(20)]
    with pytest.raises(MemoOverflow):
        memoize_model(set_model(), transitions, max_states=1000)
