"""Tier-1: the compile-surface prover (analysis pass 4) and the
runtime compile guard.

Contracts:

- the committed ``PROGRAMS.md`` inventory artifact matches the
  generated one (drift = failure — same pattern as the budget table);
- a mixed-shape ``check_batch`` + shrink + txn workload run under the
  compile guard observes ONLY programs inside the static inventory;
- a deliberately unbucketed shape driven through a monitored engine
  entry IS caught as an offender;
- the ``unbucketed-dispatch-site`` rule chases shape values through
  the call graph (the seeded fixture's raw ``memo.n_states`` is
  laundered through a helper);
- the ``stale-suppression`` audit flags dead markers and keeps live
  ones.
"""
from __future__ import annotations

import os
import random

import numpy as np
import pytest

from comdb2_tpu import analysis
from comdb2_tpu.analysis import compile_surface as CS
from comdb2_tpu.utils import compile_guard as CG

REPO = analysis.repo_root()
FIXTURES = os.path.join(REPO, "tests", "fixtures", "analysis")


# --- static inventory --------------------------------------------------------

def test_programs_artifact_matches_committed():
    """The checked-in PROGRAMS.md is exactly what the prover
    generates — regenerating it is the fix when ladders change:
    ``python -m comdb2_tpu.analysis --programs PROGRAMS.md``."""
    committed = open(os.path.join(REPO, "PROGRAMS.md")).read()
    assert CS.render_programs() == committed, \
        "PROGRAMS.md drifted from the declared ladders — regenerate " \
        "with: python -m comdb2_tpu.analysis --programs PROGRAMS.md"


def test_inventory_covers_every_engine_surface():
    inv = CS.static_inventory()
    for name in ("run", "check_device_keys", "check_device_flat",
                 "check_device_seg_batch", "check_device_batch",
                 "check_device_seg2", "closure_diag_kernel"):
        assert inv.site_for(name) is not None, name


def test_inventory_matching():
    inv = CS.static_inventory()

    def rec(name, *shapes):
        return CG.CompileRecord(name=name, shapes=shapes,
                                dtypes=("int32",) * len(shapes))

    # a bucketed keys-engine signature is inside the surface
    ok = rec("check_device_keys", (16, 16), (8, 4, 2), (8, 4, 2),
             (8, 4), (8,))
    assert inv.matches(ok)
    # the same signature with a non-pow2 table dim is an offender
    bad = rec("check_device_keys", (24, 24), (8, 4, 2), (8, 4, 2),
              (8, 4), (8,))
    assert not inv.matches(bad)
    # closure bucket; then a non-pow2 N
    assert inv.matches(rec("closure_diag_kernel", (4, 64, 8)))
    assert not inv.matches(rec("closure_diag_kernel", (4, 24, 3)))
    # an unknown jit name is outside the surface unless infra-listed
    assert not inv.matches(rec("rogue_engine", (1000, 1000)))
    assert inv.matches(rec("convert_element_type", ()))
    assert inv.offenders([ok, bad]) == [bad]


def test_witnesses_trace_clean():
    """Every ladder witness still traces through the real entry
    points (jax.eval_shape — no compile)."""
    findings = CS.trace_witnesses()
    assert findings == [], [f.format() for f in findings]


# --- runtime guard -----------------------------------------------------------

def test_parse_compile_log():
    rec = CG.parse_compile_log(
        "Compiling check_device_keys with global shapes and types "
        "[ShapedArray(int32[16,16]), ShapedArray(int32[8,4,2]), "
        "ShapedArray(int32[])]. Argument mapping: (x, y, z).")
    assert rec is not None
    assert rec.name == "check_device_keys"
    assert rec.shapes == ((16, 16), (8, 4, 2), ())
    assert rec.dtypes == ("int32", "int32", "int32")
    assert CG.parse_compile_log("Finished tracing foo") is None


def test_guard_mixed_workload_stays_inside_inventory():
    """The acceptance workload: mixed-shape check_batch + shrink +
    txn closure under the guard — observed compiles ⊆ static
    inventory."""
    from comdb2_tpu.checker.batch import check_batch, pack_batch
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops import op as O
    from comdb2_tpu.ops.synth import register_history
    from comdb2_tpu.shrink import Shrinker
    from comdb2_tpu.txn import closure_jax as CJ
    from comdb2_tpu.utils import next_pow2

    inv = CS.static_inventory()
    rng = random.Random(7)
    with CG.guard() as g:
        # two shape buckets through the batched XLA engines
        for n_ev, B in ((24, 4), (48, 8)):
            hs = [register_history(rng, n_procs=3, n_events=n_ev,
                                   p_info=0.0) for _ in range(B)]
            batch = pack_batch(hs, cas_register())
            ns = next_pow2(batch.memo.n_states)
            nt = next_pow2(batch.memo.n_transitions)
            for engine in ("keys", "flat"):
                status, _, _ = check_batch(
                    batch, F=64, engine=engine, s_pad=8, k_pad=2,
                    n_states_pad=ns, n_transitions_pad=nt)
                assert (np.asarray(status) == 0).all()
        # shrink: pow2 kept-op buckets through check_batch
        seed = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
                O.invoke(1, "write", 2), O.ok(1, "write", 2),
                O.invoke(2, "read", None), O.Op(2, "ok", "read", 1)]
        for _ in range(8):
            seed += [O.invoke(3, "write", 3), O.ok(3, "write", 3)]
        job = Shrinker(seed, "cas-register", F=64)
        steps = 0
        while not job.step() and steps < 32:
            steps += 1
        assert job.error is None
        # txn closure: two N buckets, single and batched
        CJ.closure_diag(np.zeros((4, 16, 16), bool))
        CJ.closure_diag_batch(np.zeros((2, 4, 32, 32), bool))

    off = g.offenders(inv)
    assert off == [], [r.format() for r in off]
    g.assert_closed(inv)            # the raising form agrees
    c = g.counters()
    # >= 1, not 2: the witness test may have pre-built the N=16
    # closure program in this process (the counter diffs NEW builds)
    assert c["closure_programs"] >= 1
    assert c["xla_lowerings"] >= 4  # at least the 2x2 engine programs
    assert any(r.name == "closure_diag_kernel" for r in g.records)


def test_guard_catches_deliberately_unbucketed_shape():
    from comdb2_tpu.checker import linear_jax as LJ

    inv = CS.static_inventory()
    with CG.guard() as g:
        succ = np.full((24, 24), -1, np.int32)    # 24: not a pow2
        ip = np.full((8, 4, 2), -1, np.int32)
        it = np.zeros((8, 4, 2), np.int32)
        okp = np.full((8, 4), -1, np.int32)
        dp = np.zeros(8, np.int32)
        LJ.check_device_keys(succ, ip, it, okp, dp, B=4, F=64, P=2,
                             n_states=24, n_transitions=24)
    off = g.offenders(inv)
    assert any(r.name == "check_device_keys" and (24, 24) in r.shapes
               for r in off), [r.format() for r in g.records]
    with pytest.raises(CG.CompileSurfaceError):
        g.assert_closed(inv)


# --- the unbucketed-dispatch-site rule ---------------------------------------

def test_unbucketed_rule_is_interprocedural():
    path = os.path.join(FIXTURES, "bad_unbucketed_dispatch.py")
    findings = CS.scan_files([path])
    rules = {f.rule for f in findings}
    assert rules == {"unbucketed-dispatch-site"}
    msgs = " ".join(f.message for f in findings)
    # the helper-laundered raw memo count is chased to its call site
    assert "via _dispatch" in msgs
    # the direct len(...) case is caught without the chase
    assert "len(" in msgs


def test_unbucketed_rule_accepts_sanctioned_values():
    src = (
        "from comdb2_tpu.checker.batch import check_batch\n"
        "from comdb2_tpu.utils import next_pow2\n"
        "def serve(batch, items):\n"
        "    return check_batch(batch, s_pad=64,\n"
        "                       n_states_pad=next_pow2(len(items)))\n")
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "ok_site.py")
        with open(p, "w") as fh:
            fh.write(src)
        assert CS.scan_files([p]) == []


def test_unbucketed_rule_uses_last_dominating_assignment(tmp_path):
    """Reassignment resolves to the LAST assignment before the sink,
    in both directions: sanitizing a raw value clears the finding,
    and re-rawing a sanctioned name flags."""
    clean = tmp_path / "clean.py"
    clean.write_text(
        "from comdb2_tpu.checker.batch import check_batch\n"
        "from comdb2_tpu.utils import next_pow2\n"
        "def serve(batch, items):\n"
        "    n = len(items)\n"
        "    n = next_pow2(n)\n"
        "    return check_batch(batch, s_pad=n)\n")
    assert CS.scan_files([str(clean)]) == []
    rawed = tmp_path / "rawed.py"
    rawed.write_text(
        "from comdb2_tpu.checker.batch import check_batch\n"
        "from comdb2_tpu.utils import next_pow2\n"
        "def serve(batch, items):\n"
        "    n = next_pow2(8)\n"
        "    n = len(items)\n"
        "    return check_batch(batch, s_pad=n)\n")
    assert [f.rule for f in CS.scan_files([str(rawed)])] \
        == ["unbucketed-dispatch-site"]


def test_unbucketed_rule_suppressible():
    src = (
        "from comdb2_tpu.checker.batch import check_batch\n"
        "def serve(batch, items):\n"
        "    return check_batch(batch, s_pad=len(items))"
        "  # analysis: ignore[unbucketed-dispatch-site]\n")
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "sup_site.py")
        with open(p, "w") as fh:
            fh.write(src)
        assert CS.scan_files([p]) == []
        assert CS.scan_files([p], apply_suppressions=False) != []


# --- stale-suppression audit -------------------------------------------------

def test_stale_suppression_fixture():
    path = os.path.join(FIXTURES, "bad_stale_suppression.py")
    findings = analysis.audit_suppressions([path])
    assert [f.rule for f in findings] == ["stale-suppression"]
    assert "hash-dedup" in findings[0].message


def test_live_suppression_not_flagged(tmp_path):
    # a marker whose rule DOES trip on its line is live, not stale
    live = tmp_path / "live.py"
    live.write_text(
        "import os\nimport jax\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'"
        "  # analysis: ignore[jax-env-after-import]\n")
    assert analysis.audit_suppressions([str(live)]) == []


def test_marker_text_in_string_literal_is_not_a_marker(tmp_path):
    # prose mentioning the marker (docstrings, test sources) must not
    # be audited as a suppression — only real comments count
    prose = tmp_path / "prose.py"
    prose.write_text(
        'DOC = "append # analysis: ignore[hash-dedup] to the line"\n')
    assert analysis.audit_suppressions([str(prose)]) == []


def test_blanket_stale_marker_cannot_self_suppress(tmp_path):
    # a blanket marker on a clean line is stale even though blanket
    # markers suppress every OTHER rule on their line
    f = tmp_path / "blanket.py"
    f.write_text("x = 1  # analysis: ignore\n")
    findings = analysis.audit_suppressions([str(f)])
    assert [f_.rule for f_ in findings] == ["stale-suppression"]
