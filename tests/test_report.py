"""Reporting layer tests: perf math, SVG/HTML artifact generation."""

import os
import random

from comdb2_tpu.checker import linear
from comdb2_tpu.models import model as M
from comdb2_tpu.ops.op import invoke, ok, fail, info, Op
from comdb2_tpu.ops.synth import register_history
from comdb2_tpu.report import (perf, timeline, linear_svg, latency_graph,
                               perf_checker, Timeline)

TEST = {"name": "report-test"}
SEC = 1_000_000_000


def _timed_history():
    h = []
    t = 0
    for i in range(40):
        p = i % 4
        t += SEC // 10
        h.append(invoke(p, "write", i, time=t))
        t += SEC // 100
        typ = "ok" if i % 5 else "fail"
        h.append(Op(p, typ, "write", i, time=t))
    h.insert(10, Op("nemesis", "info", "start", None, time=SEC))
    h.insert(30, Op("nemesis", "info", "stop", None, time=3 * SEC))
    return h


def test_history_latencies_pairs():
    h = [invoke(0, "w", 1, time=100), ok(0, "w", 1, time=350)]
    ps = perf.history_latencies(h)
    assert len(ps) == 1
    assert ps[0][1].time - ps[0][0].time == 250


def test_nemesis_intervals():
    h = [Op("nemesis", "info", "start", None, time=1 * SEC),
         Op("nemesis", "info", "stop", None, time=2 * SEC),
         Op("nemesis", "info", "start", None, time=3 * SEC)]
    iv = perf.nemesis_intervals(h, final_time=5.0)
    assert iv == [(1.0, 2.0), (3.0, 5.0)]


def test_quantiles_floor_semantics():
    # perf.clj:45-56 — index = floor(n*q), clamped
    q = perf.quantiles([0.5, 1], [1, 2, 3, 4])
    assert q[0.5] == 3
    assert q[1] == 4


def test_latencies_to_quantiles_buckets():
    pts = [(1, 10.0), (2, 20.0), (40, 100.0)]
    curves = perf.latencies_to_quantiles(30, [1], pts)
    assert curves[1] == [(15.0, 20.0), (45.0, 100.0)]


def test_graphs_produce_svg(tmp_path):
    h = _timed_history()
    s1 = perf.point_graph(TEST, h, str(tmp_path / "latency-raw.svg"))
    s2 = perf.quantiles_graph(TEST, h)
    s3 = perf.rate_graph(TEST, h)
    for s in (s1, s2, s3):
        assert s.startswith("<svg") and s.endswith("</svg>")
    assert (tmp_path / "latency-raw.svg").exists()


def test_perf_checker_writes_artifacts(tmp_path):
    test = {"name": "t", "dir": str(tmp_path)}
    r = perf_checker().check(test, None, _timed_history())
    assert r["valid?"] is True
    assert (tmp_path / "latency-raw.svg").exists()
    assert (tmp_path / "latency-quantiles.svg").exists()
    assert (tmp_path / "rate.svg").exists()


def test_timeline_html(tmp_path):
    h = _timed_history()
    doc = timeline.html(TEST, h, str(tmp_path / "timeline.html"))
    assert "<html>" in doc and 'class="op ok"' in doc \
        and 'class="op fail"' in doc
    assert (tmp_path / "timeline.html").exists()
    r = Timeline().check({"name": "t", "dir": str(tmp_path)}, None, h)
    assert r["valid?"] is True


def test_timeline_pairs_unmatched_info():
    h = [info("nemesis", "start", None), invoke(0, "w", 1), ok(0, "w", 1)]
    ps = timeline.pairs(h)
    assert ps[0][1] is None            # singleton info
    assert ps[1][0].type == "invoke"


def test_counterexample_svg(tmp_path):
    h = [invoke(0, "write", 1), ok(0, "write", 1),
         invoke(1, "read", None), ok(1, "read", 2)]
    a = linear.analysis(M.register(), h)
    assert a.valid is False
    svg = linear_svg.render_analysis(h, a, str(tmp_path / "linear.svg"))
    assert svg.startswith("<svg")
    assert "frontier died here" in svg
    assert (tmp_path / "linear.svg").exists()


def test_counterexample_svg_large_history_windows():
    rng = random.Random(5)
    h = register_history(rng, n_procs=4, n_events=400, p_info=0.0)
    # corrupt the last ok to make it invalid near the end
    for i in range(len(h) - 1, -1, -1):
        if h[i].type == "ok" and h[i].f == "read":
            h[i] = h[i].with_(value=99)
            break
    a = linear.analysis(M.cas_register(), h)
    assert a.valid is False
    svg = linear_svg.render_analysis(h, a)
    assert svg.startswith("<svg")


def test_counterexample_paths_rendered():
    """INVALID analyses carry concrete failed linearization orders
    (final paths, linear.clj:180-212) and the SVG renders them."""
    h = [invoke(0, "write", 1), ok(0, "write", 1),
         invoke(1, "read", None), ok(1, "read", 2)]
    a = linear.analysis(M.register(), h, backend="device")
    assert a.valid is False
    paths = a.info.get("paths")
    assert paths, a.info
    # every path ends at the inconsistency that killed it
    for p in paths:
        assert p[-1]["model"] == "inconsistent"
    svg = linear_svg.render_analysis(h, a)
    assert "failed linearization orders" in svg


def test_counterexample_bounded_on_long_history():
    """Decoding an INVALID verdict late in a long history must replay
    only a bounded window on host (round-1 Weak #3), agree with the
    device fail index, and produce paths + SVG quickly."""
    import time as _time

    rng = random.Random(11)
    h = register_history(rng, n_procs=4, n_events=4000, p_info=0.0)
    for i in range(len(h) - 1, -1, -1):
        if h[i].type == "ok" and h[i].f == "read":
            h[i] = h[i].with_(value=99)
            break
    t0 = _time.monotonic()
    a = linear.analysis(M.cas_register(), h, backend="device")
    dt = _time.monotonic() - t0
    assert a.valid is False
    assert a.info.get("paths"), a.info
    # the decoded op index is the device fail index (same engine family
    # reproduces the same death point)
    assert a.op_index is not None and h[a.op_index].type == "ok"
    # analysis ops come from the completed/indexed history
    assert (a.op.process, a.op.type, a.op.f, a.op.value) == (
        h[a.op_index].process, "ok", h[a.op_index].f,
        h[a.op_index].value)
    svg = linear_svg.render_analysis(h, a)
    assert "failed linearization orders" in svg
    # bounded: the whole analysis incl. reconstruction stays fast even
    # with the search + decode + render (CPU mesh; generous bound)
    assert dt < 120, dt


def test_linearizable_checker_writes_svg_on_failure(tmp_path):
    """An INVALID verdict drops linear.svg into the test dir — the
    reference's render-analysis! on failure (checker.clj:71-85)."""
    from comdb2_tpu.checker import checkers as C

    h = [invoke(0, "write", 1), ok(0, "write", 1),
         invoke(1, "read", None), ok(1, "read", 2)]
    out = C.Linearizable(backend="host").check(
        {"dir": str(tmp_path)}, M.register(), h)
    assert out["valid?"] is False
    svg = (tmp_path / "linear.svg")
    assert svg.exists()
    assert "frontier died here" in svg.read_text()


def test_independent_failures_get_per_key_svgs(tmp_path):
    """Each failing key's counterexample SVG lands under
    independent/<k>/ — keys must not clobber one shared linear.svg."""
    from comdb2_tpu.checker import checkers as C
    from comdb2_tpu.checker import independent as I
    from comdb2_tpu.ops import op as O
    from comdb2_tpu.ops.kv import tuple_

    h = []
    for k in (3, 7):
        h += [O.invoke(k, "write", tuple_(k, 1)),
              O.ok(k, "write", tuple_(k, 1)),
              O.invoke(k, "read", tuple_(k, None)),
              O.ok(k, "read", tuple_(k, 2))]
    r = I.checker(C.Linearizable(backend="host")).check(
        {"dir": str(tmp_path)}, M.register(), h)
    assert r["valid?"] is False and sorted(r["failures"]) == [3, 7]
    for k in (3, 7):
        assert (tmp_path / "independent" / str(k) / "linear.svg").exists()


def test_counterexample_paths_rendered_spatially():
    """Failed linearization orders render SPATIALLY over the time grid
    (knossos/linear/report.clj:385-647): each path is an arrow chain
    hopping between the ops' bars, every hop labeled with the model
    state it produced, the inconsistent hop red — not just text
    chips."""
    h = [invoke(0, "write", 1), ok(0, "write", 1),
         invoke(1, "read", None), ok(1, "read", 2)]
    a = linear.analysis(M.register(), h, backend="device")
    assert a.valid is False
    assert a.info.get("paths"), a.info
    svg = linear_svg.render_analysis(h, a)
    # spatial chain: anchored circles on the grid + the overlay note
    assert "drawn over the grid" in svg
    assert svg.count("<circle") >= 1
    # the inconsistent hop is drawn in the failure color
    assert "#c0392b" in svg


def test_warp_time_coordinates_compresses_dead_regions():
    """The density warp (knossos/linear/report.clj:385-410): dense
    regions keep full resolution, empty stretches collapse — and the
    map stays monotone."""
    spans = ([(0, float(t), float(t + 1))
              for t in range(0, 10) for _ in range(2)]
             + [(0, float(t), float(t + 1))
                for t in range(90, 100) for _ in range(2)])
    f = linear_svg.warp_time_coordinates(spans, 0.0, 100.0)
    xs = [f(t) for t in range(0, 101, 1)]
    assert all(b >= a for a, b in zip(xs, xs[1:]))     # monotone
    assert abs(xs[0]) < 1e-9 and abs(xs[-1] - 1.0) < 1e-9
    dense_w = f(10) - f(0)
    dead_w = f(90) - f(10)
    # the dead 80% of the axis must take LESS width than the dense
    # first 10% (uniform coordinates would give it 8x more)
    assert dead_w < dense_w, (dead_w, dense_w)


def test_render_uses_real_time_axis_when_present():
    """Histories with timestamps render on the warped real-time axis:
    a huge dead gap between two op clusters must not push the later
    cluster off proportionally (rank fallback is only for time-less
    histories)."""
    h = [invoke(0, "write", 1, time=0), ok(0, "write", 1, time=10),
         invoke(1, "write", 2, time=20), ok(1, "write", 2, time=30),
         # dead gap: nothing between t=30 and t=1e9
         invoke(0, "read", None, time=1_000_000_000),
         ok(0, "read", 9, time=1_000_000_010)]
    a = linear.analysis(M.cas_register(), h)
    assert a.valid is False
    svg = linear_svg.render_analysis(h, a)
    assert svg.startswith("<svg")
    assert "frontier died here" in svg


def test_all_final_paths_render_with_merged_segments():
    """ALL final paths render (no 4-path cap) and shared prefix
    segments draw once (the merge-lines role, report.clj:300-351):
    with N paths from one frontier the number of drawn path segments
    is far below the sum of path lengths."""
    rng = random.Random(7)
    h = register_history(rng, n_procs=5, n_events=60, p_info=0.0)
    # five concurrent pending writes right before a failing read give
    # the reconstruction many distinct linearization orders
    base = len(h)
    for p in range(100, 105):
        h.append(invoke(p, "write", p % 5))
    h.append(invoke(99, "read", None))
    h.append(ok(99, "read", 77))          # impossible value
    a = linear.analysis(M.cas_register(), h, backend="device")
    assert a.valid is False
    paths = a.info.get("paths")
    assert paths and len(paths) >= 5, a.info
    svg = linear_svg.render_analysis(h, a)
    n = len(paths)
    assert f"{n} failed linearization orders" in svg, svg[:400]


def test_50k_op_invalid_renders_all_paths_warped():
    """A 50k-op INVALID renders in bounded time with the real-time
    warped axis and every reconstructed path (round-4 VERDICT #10's
    done-bar)."""
    import time as _time

    rng = random.Random(13)
    h = register_history(rng, n_procs=5, n_events=100_000, p_info=0.0)
    # timestamps: 1ms per event with a long dead gap mid-history
    h = [op.with_(time=i * 1_000_000 +
                  (3_600_000_000_000 if i > 60_000 else 0))
         for i, op in enumerate(h)]
    for i in range(len(h) - 1, -1, -1):
        if h[i].type == "ok" and h[i].f == "read":
            h[i] = h[i].with_(value=99)
            break
    a = linear.analysis(M.cas_register(), h, backend="device")
    assert a.valid is False
    assert a.info.get("paths"), a.info
    t0 = _time.monotonic()
    svg = linear_svg.render_analysis(h, a)
    dt = _time.monotonic() - t0
    assert dt < 10, dt                      # render itself is bounded
    assert "failed linearization orders" in svg
    assert "frontier died here" in svg


def test_host_backend_invalid_carries_final_paths():
    """The host engine's INVALID analyses carry final paths too (the
    reference's analysis always does, linear.clj:251-265) — without
    them, small below-threshold histories rendered counterexample SVGs
    with no linearization orders at all (round-5 find)."""
    h = [invoke(0, "write", 1), ok(0, "write", 1),
         invoke(1, "read", None), ok(1, "read", 2)]
    a = linear.analysis(M.register(), h, backend="host")
    assert a.valid is False
    assert a.info.get("backend") == "host"
    paths = a.info.get("paths")
    assert paths, a.info
    for p in paths:
        assert p[-1]["model"] == "inconsistent"
    svg = linear_svg.render_analysis(h, a)
    assert "failed linearization orders" in svg


def test_counterexample_svg_hover_structure():
    """Each anchored MULTI-STEP path carries an invisible hover
    hit-polyline (the reference highlights paths on hover,
    report.clj:540+); hovering halos the whole path, disambiguating
    merged shared segments. (Single-step paths have nothing to halo.)
    """
    rng = random.Random(7)
    h = register_history(rng, n_procs=5, n_events=60, p_info=0.0)
    for p in range(100, 105):
        h.append(invoke(p, "write", p % 5))
    h.append(invoke(99, "read", None))
    h.append(ok(99, "read", 77))
    a = linear.analysis(M.cas_register(), h, backend="device")
    assert a.valid is False
    svg = linear_svg.render_analysis(h, a)
    assert "<style>" in svg
    assert 'class="cpath"' in svg and 'class="hit"' in svg
    assert svg.count('class="cpath"') >= 5
